"""Tests for the clock shim (faketime) and the faultfs disk-fault layer.

The LD_PRELOAD shim is compiled and exercised for real (g++ is part of
the toolchain); faultfs mounting needs FUSE + root on a DB node, so its
driver is tested against the dummy remote (command routing), mirroring
how the reference tests node-touching code (SURVEY.md §4.2)."""

import os
import subprocess

import pytest

from jepsen_tpu import control, faketime, faultfs

NATIVE = os.path.join(os.path.dirname(__file__), "..", "native")


# --------------------------------------------------------------------------
# faketime
# --------------------------------------------------------------------------

def test_script_contents():
    s = faketime.script("/usr/bin/db-server", -30, 1.5)
    assert s.startswith("#!/bin/bash\n")
    assert f"LD_PRELOAD={faketime.SHIM_SO}" in s
    assert "JEPSEN_FAKETIME_OFFSET_S=-30.0" in s
    assert "JEPSEN_FAKETIME_RATE=1.5" in s
    assert 'exec /usr/bin/db-server "$@"' in s


def test_rand_factor_bounds():
    import random
    rng = random.Random(0)
    vals = [faketime.rand_factor(2.5, rng) for _ in range(500)]
    hi = 2 / (1 + 1 / 2.5)
    lo = hi / 2.5
    assert all(lo <= v <= hi for v in vals)
    assert max(vals) / min(vals) <= 2.5 + 1e-9


@pytest.fixture(scope="module")
def shim_so(tmp_path_factory):
    # -pthread mirrors faketime.install's build line: the shim calls
    # pthread_once, and without the link flag a preloaded .so breaks
    # any host binary that doesn't link libpthread itself (`date` on
    # current glibc fails with "undefined symbol: pthread_once" —
    # the cause of the old test_shim_offset failure)
    out = tmp_path_factory.mktemp("shim") / "libfaketime_shim.so"
    r = subprocess.run(
        ["g++", "-O2", "-fPIC", "-shared", "-pthread", "-o", str(out),
         os.path.join(NATIVE, "faketime_shim.cc"), "-ldl"],
        capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"shim build failed: {r.stderr[:200]}")
    return str(out)


def test_shim_offset(shim_so):
    env = dict(os.environ, LD_PRELOAD=shim_so,
               JEPSEN_FAKETIME_OFFSET_S="7200")
    faked = int(subprocess.run(["date", "+%s"], env=env,
                               capture_output=True, text=True).stdout)
    real = int(subprocess.run(["date", "+%s"],
                              capture_output=True, text=True).stdout)
    assert 7190 < faked - real < 7210


def test_shim_rate(shim_so):
    env = dict(os.environ, LD_PRELOAD=shim_so, JEPSEN_FAKETIME_RATE="8")
    out = subprocess.run(
        ["python3", "-c",
         "import time; a=time.time(); time.sleep(0.3); print(time.time()-a)"],
        env=env, capture_output=True, text=True)
    dt = float(out.stdout)
    assert 1.8 < dt < 3.5  # 0.3 real seconds at 8x, some slop


def test_wrap_unwrap_against_dummy():
    test = {"nodes": ["n1"], "ssh": {"dummy": True}}
    remote = control.remote_for(test)

    def act(t, n):
        faketime.wrap("/usr/bin/db", 10, 2.0)
        faketime.unwrap("/usr/bin/db")

    control.on_nodes(test, act)
    cmds = [p for _, kind, p in remote.actions if kind == "execute"]
    # dummy exists() always answers yes, so wrap takes the
    # "already wrapped" branch: rewrite wrapper + chmod, then unwrap's mv
    assert any("JEPSEN_FAKETIME_RATE=2.0" in c for c in cmds)
    assert any("chmod a+x /usr/bin/db" in c for c in cmds)
    assert any("mv /usr/bin/db.no-faketime /usr/bin/db" in c for c in cmds)


# --------------------------------------------------------------------------
# faultfs
# --------------------------------------------------------------------------

def test_faultfs_source_present_and_plausible():
    src = open(os.path.join(NATIVE, "faultfs.cc")).read()
    assert "fuse_main" in src
    assert ".faultfs-ctl" in src


def test_faultfs_nemesis_routing():
    test = {"nodes": ["n1", "n2"], "ssh": {"dummy": True}}
    remote = control.remote_for(test)
    nem = faultfs.nemesis()
    op = nem.invoke(test, {"type": "info", "f": "break-all", "value": None})
    assert op["type"] == "info"
    cmds = [(n, p) for n, kind, p in remote.actions if kind == "execute"]
    eio = [(n, c) for n, c in cmds if "eio 1" in c and faultfs.CTL in c]
    assert {n for n, _ in eio} == {"n1", "n2"}

    remote.actions.clear()
    nem.invoke(test, {"type": "info", "f": "break-pct", "value": 0.05})
    assert any("eio 0.05" in c for _, k, c in remote.actions
               if k == "execute")

    remote.actions.clear()
    nem.invoke(test, {"type": "info", "f": "clear", "value": ["n2"]})
    clr = [(n, c) for n, k, c in remote.actions
           if k == "execute" and "clear" in c]
    assert {n for n, _ in clr} == {"n2"}


def test_faultfs_nemesis_setup_installs_everywhere():
    test = {"nodes": ["n1"], "ssh": {"dummy": True}}
    remote = control.remote_for(test)
    faultfs.nemesis().setup(test)
    kinds = [(k, p) for _, k, p in remote.actions]
    assert any(k == "upload" for k, _ in kinds)
    assert any(k == "execute" and "g++" in str(p) for k, p in kinds)
    assert any(k == "execute" and faultfs.MOUNT_DIR in str(p)
               for k, p in kinds)


def test_faultfs_unknown_op_raises():
    test = {"nodes": ["n1"], "ssh": {"dummy": True}}
    control.remote_for(test)
    with pytest.raises(Exception):
        faultfs.nemesis().invoke(
            test, {"type": "info", "f": "bogus", "value": None})
