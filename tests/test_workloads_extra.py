"""Golden tests for the long-fork, causal, causal-reverse, adya, and
generic-cycle workloads (reference behaviors: tests/long_fork.clj,
causal.clj, causal_reverse.clj, adya.clj, cycle.clj)."""

import pytest

from jepsen_tpu import generator as gen, independent
from jepsen_tpu.workloads import adya, causal, causal_reverse, cycle, long_fork


def ok(process, f, value):
    return {"type": "ok", "process": process, "f": f, "value": value}


def invoke(process, f, value):
    return {"type": "invoke", "process": process, "f": f, "value": value}


# --------------------------------------------------------------------------
# long fork
# --------------------------------------------------------------------------

def read(vals: dict):
    return ok(0, "read", [["r", k, v] for k, v in vals.items()])


def test_long_fork_classic_anomaly():
    # T3 sees x=nil,y=1; T4 sees x=1,y=nil — mutually incomparable.
    h = [
        invoke(0, "write", [["w", 0, 1]]), ok(0, "write", [["w", 0, 1]]),
        invoke(1, "write", [["w", 1, 1]]), ok(1, "write", [["w", 1, 1]]),
        read({0: None, 1: 1}),
        read({0: 1, 1: None}),
    ]
    res = long_fork.checker(2).check({}, h, {})
    assert res["valid?"] is False
    assert len(res["forks"]) == 1


def test_long_fork_total_order_ok():
    h = [
        invoke(0, "write", [["w", 0, 1]]), ok(0, "write", [["w", 0, 1]]),
        read({0: None, 1: None}),
        read({0: 1, 1: None}),
        read({0: 1, 1: 1}),
    ]
    res = long_fork.checker(2).check({}, h, {})
    assert res["valid?"] is True
    assert res["reads-count"] == 3
    assert res["early-read-count"] == 1
    assert res["late-read-count"] == 1


def test_long_fork_multiple_writes_unknown():
    h = [
        invoke(0, "write", [["w", 5, 1]]),
        invoke(1, "write", [["w", 5, 1]]),
    ]
    res = long_fork.checker(2).check({}, h, {})
    assert res["valid?"] == "unknown"
    assert res["error"] == ["multiple-writes", 5]


def test_long_fork_read_compare():
    assert long_fork.read_compare({1: None}, {1: None}) == 0
    assert long_fork.read_compare({1: 1}, {1: None}) == -1
    assert long_fork.read_compare({1: None}, {1: 1}) == 1
    assert long_fork.read_compare({1: 1, 2: None}, {1: None, 2: 1}) is None
    with pytest.raises(long_fork.IllegalHistory):
        long_fork.read_compare({1: 1}, {2: 1})
    with pytest.raises(long_fork.IllegalHistory):
        long_fork.read_compare({1: 1}, {1: 2})


def test_long_fork_generator_writes_then_reads_group():
    g = long_fork.LongForkGen(3, seed=0)
    ctx = gen.Context.for_test({"concurrency": 2})
    test = {}
    seen_write_then_read = False
    for _ in range(40):
        res = gen.op(g, test, ctx)
        assert res is not None
        o, g = res
        if o is gen.PENDING:
            break
        if o["f"] == "read":
            ks = [m[1] for m in o["value"]]
            assert len(ks) == 3
            assert sorted(ks) == list(long_fork.group_for(3, ks[0]))
            seen_write_then_read = True
        else:
            assert o["f"] == "write"
            assert o["value"][0][0] == "w"
    assert seen_write_then_read


def test_long_fork_workload_package():
    wl = long_fork.workload(2)
    assert "checker" in wl and "generator" in wl


# --------------------------------------------------------------------------
# causal
# --------------------------------------------------------------------------

def causal_op(f, value=None, position=None, link=None):
    return {"type": "ok", "process": 0, "f": f, "value": value,
            "position": position, "link": link}


def test_causal_valid_order():
    h = [
        causal_op("read-init", 0, position=1, link="init"),
        causal_op("write", 1, position=2, link=1),
        causal_op("read", 1, position=3, link=2),
        causal_op("write", 2, position=4, link=3),
        causal_op("read", 2, position=5, link=4),
    ]
    res = causal.check().check({}, h, {})
    assert res["valid?"] is True


def test_causal_bad_link():
    h = [
        causal_op("read-init", 0, position=1, link="init"),
        causal_op("write", 1, position=2, link=99),
    ]
    res = causal.check().check({}, h, {})
    assert res["valid?"] is False
    assert "link" in res["error"].lower() or "Cannot link" in res["error"]


def test_causal_stale_read():
    h = [
        causal_op("read-init", 0, position=1, link="init"),
        causal_op("write", 1, position=2, link=1),
        causal_op("read", 0, position=3, link=2),  # stale: register is 1
    ]
    res = causal.check().check({}, h, {})
    assert res["valid?"] is False


def test_causal_wrong_write_value():
    h = [causal_op("write", 7, position=1, link="init")]
    res = causal.check().check({}, h, {})
    assert res["valid?"] is False
    assert "expected value 1" in res["error"]


def test_causal_nil_read_ok():
    h = [causal_op("read", None, position=1, link="init")]
    assert causal.check().check({}, h, {})["valid?"] is True


# --------------------------------------------------------------------------
# causal reverse
# --------------------------------------------------------------------------

def test_causal_reverse_detects_missing_predecessor():
    h = [
        invoke(0, "write", 1), ok(0, "write", 1),
        # write 2 invoked after 1 acked: 1 must precede 2
        invoke(1, "write", 2), ok(1, "write", 2),
        # read sees 2 without 1 — anomaly
        invoke(2, "read", None), ok(2, "read", [2]),
    ]
    res = causal_reverse.checker().check({}, h, {})
    assert res["valid?"] is False
    assert res["errors"][0]["missing"] == [1]


def test_causal_reverse_concurrent_writes_ok():
    h = [
        # both writes in flight together: no precedence either way
        invoke(0, "write", 1),
        invoke(1, "write", 2),
        ok(0, "write", 1), ok(1, "write", 2),
        invoke(2, "read", None), ok(2, "read", [2]),
    ]
    res = causal_reverse.checker().check({}, h, {})
    assert res["valid?"] is True


def test_causal_reverse_full_visibility_ok():
    h = [
        invoke(0, "write", 1), ok(0, "write", 1),
        invoke(1, "write", 2), ok(1, "write", 2),
        invoke(2, "read", None), ok(2, "read", [1, 2]),
    ]
    assert causal_reverse.checker().check({}, h, {})["valid?"] is True


def test_causal_reverse_workload_package():
    wl = causal_reverse.workload(["n1", "n2", "n3"])
    assert "checker" in wl and "generator" in wl


# --------------------------------------------------------------------------
# adya g2
# --------------------------------------------------------------------------

def test_adya_g2_one_insert_per_key_ok():
    h = [
        ok(0, "insert", independent.tuple_(1, [None, 10])),
        {"type": "fail", "process": 1, "f": "insert",
         "value": independent.tuple_(1, [11, None])},
        ok(2, "insert", independent.tuple_(2, [12, None])),
    ]
    res = adya.g2_checker().check({}, h, {})
    assert res["valid?"] is True
    assert res["key-count"] == 2
    assert res["legal-count"] == 2


def test_adya_g2_double_insert_illegal():
    h = [
        ok(0, "insert", independent.tuple_(1, [None, 10])),
        ok(1, "insert", independent.tuple_(1, [11, None])),
    ]
    res = adya.g2_checker().check({}, h, {})
    assert res["valid?"] is False
    assert res["illegal"] == {1: 2}


def test_adya_gen_emits_pairs():
    g = adya.g2_gen()
    ctx = gen.Context.for_test({"concurrency": 4})
    vals = []
    for _ in range(8):
        res = gen.op(g, {}, ctx)
        if res is None:
            break
        o, g = res
        if o is gen.PENDING:
            break
        assert o["f"] == "insert"
        vals.append(o["value"])
        ctx = ctx.busy(ctx.process_to_thread(o["process"]))
    assert len(vals) >= 2
    # each value is a lifted [key, [a,b]] with exactly one side set
    for v in vals:
        assert independent.is_tuple(v)
        a, b = v.value
        assert (a is None) != (b is None)
    ids = [a or b for a, b in (v.value for v in vals)]
    assert len(set(ids)) == len(ids)


# --------------------------------------------------------------------------
# generic cycle checker
# --------------------------------------------------------------------------

def test_cycle_checker_finds_cycle():
    h = [ok(0, "txn", None), ok(1, "txn", None), ok(2, "txn", None)]

    def analyzer(history):
        return [(0, 1, "ww"), (1, 0, "ww")], lambda comp: "0<->1"

    res = cycle.checker(analyzer).check({}, h, {})
    assert res["valid?"] is False
    assert res["scc-count"] == 1
    assert res["cycles"][0]["explanation"] == "0<->1"
    assert [o["index"] for o in res["cycles"][0]["ops"]] == [0, 1]


def test_cycle_checker_acyclic():
    h = [ok(0, "txn", None), ok(1, "txn", None)]
    res = cycle.checker(lambda hist: [(0, 1, "ww")]).check({}, h, {})
    assert res["valid?"] is True


# --------------------------------------------------------------------------
# long-fork end-to-end through the runner (atomic store => no forks)
# --------------------------------------------------------------------------

def test_long_fork_full_run(tmp_path):
    import threading

    from jepsen_tpu import client as jclient, core, db as jdb, net as jnet
    from jepsen_tpu.store import Store

    kv: dict = {}
    lock = threading.Lock()

    class KVClient(jclient.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op):
            with lock:
                if op["f"] == "write":
                    for _, k, v in op["value"]:
                        kv[k] = v
                    return {**op, "type": "ok"}
                out = [["r", k, kv.get(k)] for _, k, _ in op["value"]]
                return {**op, "type": "ok", "value": out}

    wl = long_fork.workload(2)
    test = {
        "name": "long-fork-itest",
        "nodes": ["n1", "n2", "n3"],
        "concurrency": 4,
        "ssh": {"dummy": True},
        "net": jnet.noop(),
        "db": jdb.noop(),
        "client": KVClient(),
        "store": Store(tmp_path / "store"),
        "generator": gen.clients(gen.limit(200, wl["generator"])),
        "checker": wl["checker"],
    }
    test = core.run(test)
    res = test["results"]
    assert res["valid?"] is True
    assert res["reads-count"] > 0


def test_bank_plot_renders(tmp_path):
    """Balance-over-time plot (bank.clj:160-186): ok reads become
    per-account series in bank.png."""
    from jepsen_tpu.store import Store
    from jepsen_tpu.workloads import bank as bank_wl

    hist = []
    bal = {0: 60, 1: 40}
    for i in range(5):
        bal = {0: bal[0] - 5, 1: bal[1] + 5}
        hist.append({"type": "invoke", "f": "read", "process": 0,
                     "time": i * 10**9, "index": 2 * i})
        hist.append({"type": "ok", "f": "read", "process": 0,
                     "value": dict(bal), "time": i * 10**9 + 100,
                     "index": 2 * i + 1})
    test = {"name": "bank-plot", "start-time": "t0",
            "store": Store(tmp_path / "store")}
    r = bank_wl.plot_checker().check(test, hist, {})
    assert r["valid?"] is True
    from pathlib import Path
    assert Path(r["plot"]).exists()
    assert Path(r["plot"]).name == "bank.png"


class TestLongForkVectorized:
    """The matmul formulation must agree with the pairwise comparator
    (BASELINE config #5's blockwise long-fork search)."""

    @staticmethod
    def _read_op(vals: dict):
        return {"type": "ok", "f": "txn",
                "value": [["r", k, v] for k, v in vals.items()]}

    def test_matches_pairwise_random(self):
        import random as _r
        from jepsen_tpu.workloads import long_fork as lf
        rng = _r.Random(4)
        for trial in range(30):
            n = rng.choice([2, 3, 5])
            R = rng.randrange(2, 12)
            keys = list(range(n))
            ops = [self._read_op({k: rng.choice([None, 1]) for k in keys})
                   for _ in range(R)]
            a = {(id(x), id(y)) for x, y in lf.find_forks(ops)}
            b = {(id(x), id(y)) for x, y in lf.find_forks_vectorized(ops)}
            assert a == b, trial

    def test_finds_classic_fork(self):
        from jepsen_tpu.workloads import long_fork as lf
        ops = [self._read_op({0: 1, 1: None}),
               self._read_op({0: None, 1: 1})]
        assert len(lf.find_forks_vectorized(ops)) == 1
        assert lf.find_forks_vectorized([ops[0]]) == []

    def test_illegal_values_raise(self):
        import pytest as _pytest
        from jepsen_tpu.workloads import long_fork as lf
        ops = [self._read_op({0: 1, 1: None}),
               self._read_op({0: 2, 1: None})]
        with _pytest.raises(lf.IllegalHistory):
            lf.find_forks_vectorized(ops)
        # same non-nil value everywhere is legal (matches pairwise)
        ops2 = [self._read_op({0: 7, 1: None}),
                self._read_op({0: 7, 1: 1})]
        assert lf.find_forks_vectorized(ops2) == []

    def test_checker_uses_vectorized_for_big_groups(self, monkeypatch):
        from jepsen_tpu.workloads import long_fork as lf
        calls = []
        orig = lf.find_forks_vectorized
        monkeypatch.setattr(lf, "find_forks_vectorized",
                            lambda g: calls.append(len(g)) or orig(g))
        hist = []
        for k in (0, 1):
            hist.append({"type": "invoke", "process": k, "f": "txn",
                         "value": [["w", k, 1]]})
            hist.append({"type": "ok", "process": k, "f": "txn",
                         "value": [["w", k, 1]]})
        for _ in range(lf.VECTORIZE_THRESHOLD + 1):
            hist.append({"type": "invoke", "process": 2, "f": "txn",
                         "value": [["r", 0, None], ["r", 1, None]]})
            hist.append({"type": "ok", "process": 2, "f": "txn",
                         "value": [["r", 0, 1], ["r", 1, 1]]})
        res = lf.checker(2).check({}, hist, {})
        assert res["valid?"] is True
        assert calls and calls[0] > lf.VECTORIZE_THRESHOLD
