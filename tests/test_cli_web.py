"""CLI and web UI tests."""

import json
import threading
import urllib.request

from jepsen_tpu import checker as jchecker
from jepsen_tpu import cli, generator as gen, web, workloads
from jepsen_tpu.store import Store


def make_test_fn(tmp_path):
    def test_fn(base, args):
        db, client = workloads.atom_fixtures()
        return {
            **base,
            "name": "cli-test",
            "nodes": base.get("nodes") or ["n1", "n2"],
            "db": db,
            "client": client,
            "generator": gen.clients(
                gen.limit(20, gen.repeat_gen({"f": "read"}))),
            "checker": jchecker.stats(),
            "store": Store(tmp_path / "store"),
        }

    return test_fn


def test_cli_test_command(tmp_path, capsys):
    code = cli.run_cli(make_test_fn(tmp_path),
                       argv=["test", "--dummy", "--concurrency", "2"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert code == 0
    assert out["valid?"] is True


def test_cli_analyze_command(tmp_path, capsys):
    test_fn = make_test_fn(tmp_path)
    assert cli.run_cli(test_fn, argv=["test", "--dummy"]) == 0
    capsys.readouterr()
    code = cli.run_cli(test_fn, argv=["analyze", "--store",
                                      str(tmp_path / "store")])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert code == 0
    assert out["valid?"] is True


def test_cli_invalid_exit_code(tmp_path, capsys):
    class AlwaysInvalid(jchecker.Checker):
        def check(self, test, history, opts):
            return {"valid?": False}

    def test_fn(base, args):
        t = make_test_fn(tmp_path)(base, args)
        t["checker"] = AlwaysInvalid()
        return t

    assert cli.run_cli(test_fn, argv=["test", "--dummy"]) == 1


def test_cli_usage_error(tmp_path):
    assert cli.run_cli(make_test_fn(tmp_path), argv=["bogus"]) == 254


def test_web_serves_store(tmp_path, capsys):
    # Build a store with one run.
    cli.run_cli(make_test_fn(tmp_path), argv=["test", "--dummy"])
    store = Store(tmp_path / "store")
    srv = web.make_server(store, "127.0.0.1", 0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        home = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/").read().decode()
        assert "cli-test" in home and "valid" in home
        # run dir listing
        import re
        m = re.search(r"href='/files/([^']+)'", home)
        run = m.group(1)
        listing = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/files/{run}").read().decode()
        assert "history.edn" in listing
        # file fetch
        hist = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/files/{run}/history.edn").read()
        assert b":invoke" in hist
        # zip export
        z = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/zip/{run}").read()
        assert z[:2] == b"PK"
        # traversal guard
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/files/../../etc/passwd")
            raised = False
        except urllib.error.HTTPError as e:
            raised = e.code == 404
        assert raised
    finally:
        srv.shutdown()
