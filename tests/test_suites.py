"""Per-DB suite tests: test-map assembly, DB command routing against the
dummy remote, the etcd HTTP client against an in-process fake etcd, and
a full matrix-workload run with an in-process client (SURVEY.md §4.2's
fake-backend strategy)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from jepsen_tpu import control, core, generator as gen, independent
from jepsen_tpu import client as jclient, net as jnet
from jepsen_tpu.store import Store
from jepsen_tpu.suites import (base_opts, cockroach, dgraph, etcd,
                               standard_workloads, suite_test, tidb,
                               yugabyte)


# --------------------------------------------------------------------------
# registry / assembly
# --------------------------------------------------------------------------

def test_standard_workloads_resolve():
    for name, fn in standard_workloads(base_opts()).items():
        pkg = fn()
        assert pkg.get("generator") is not None, name
        assert pkg.get("checker") is not None, name


@pytest.mark.parametrize("mod,default", [
    (cockroach, "register"), (tidb, "append"),
    (yugabyte, "bank"), (dgraph, "bank")])
def test_suite_test_maps(mod, default):
    t = getattr(mod, f"{mod.__name__.split('.')[-1]}_test")({})
    assert t["db"] is not None
    assert t["generator"] is not None
    assert t["checker"] is not None
    assert t["workload"] == default


def test_unknown_workload_raises():
    with pytest.raises(ValueError, match="unknown workload"):
        suite_test("x", "nope", base_opts(), standard_workloads())


def test_yugabyte_sweep_covers_apis_and_workloads():
    tests = yugabyte.all_tests({})
    names = {(t["api"], t["workload"]) for t in tests}
    want = sum(len(yugabyte.workloads(api=a)) for a in yugabyte.APIS)
    assert len(names) == want
    # YCQL must only sweep workloads its client supports
    from jepsen_tpu.suites import ycql
    for api, w in names:
        if api == "ycql":
            assert w in ycql.MODES


# --------------------------------------------------------------------------
# DB lifecycle against the dummy remote
# --------------------------------------------------------------------------

@pytest.mark.parametrize("dbf,needle", [
    (lambda: etcd.EtcdDB(), "--initial-cluster"),
    (lambda: cockroach.CockroachDB(), "--join"),
    (lambda: tidb.TiDB(), "tikv-server"),
    (lambda: yugabyte.YugaByteDB(), "yb-tserver"),
    (lambda: dgraph.DgraphDB(), "alpha"),
])
def test_db_setup_commands(dbf, needle):
    test = {"nodes": ["n1", "n2", "n3"], "ssh": {"dummy": True}}
    remote = control.remote_for(test)
    db = dbf()
    control.on_nodes(test, db.setup)
    cmds = " || ".join(str(p) for _, k, p in remote.actions
                       if k == "execute")
    assert needle in cmds
    remote.actions.clear()
    control.on_nodes(test, db.teardown)
    assert any("rm -rf" in str(p) for _, k, p in remote.actions
               if k == "execute")
    assert db.log_files(test, "n1")


# --------------------------------------------------------------------------
# etcd client against a fake in-process etcd (v2 HTTP API)
# --------------------------------------------------------------------------

class FakeEtcd(BaseHTTPRequestHandler):
    store = {}
    lock = threading.Lock()

    def log_message(self, *a):
        pass

    def _reply(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        key = urlparse(self.path).path.rsplit("/", 1)[-1]
        with self.lock:
            if key not in self.store:
                return self._reply(404, {"errorCode": 100})
            return self._reply(200, {"node": {"value": str(self.store[key])}})

    def do_PUT(self):
        u = urlparse(self.path)
        key = u.path.rsplit("/", 1)[-1]
        q = parse_qs(u.query)
        n = int(self.headers.get("Content-Length", 0))
        form = parse_qs(self.rfile.read(n).decode())
        value = form.get("value", [None])[0]
        with self.lock:
            if "prevValue" in q:
                cur = self.store.get(key)
                if cur is None:
                    return self._reply(404, {"errorCode": 100})
                if str(cur) != q["prevValue"][0]:
                    return self._reply(412, {"errorCode": 101})
            self.store[key] = value
            return self._reply(200, {"node": {"value": value}})


@pytest.fixture()
def fake_etcd():
    FakeEtcd.store = {}
    srv = HTTPServer(("127.0.0.1", 0), FakeEtcd)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    yield srv.server_address[1]
    srv.shutdown()


def test_etcd_client_read_write_cas(fake_etcd, monkeypatch):
    monkeypatch.setattr(etcd, "client_url",
                        lambda node: f"http://127.0.0.1:{fake_etcd}")
    c = etcd.EtcdClient().open({}, "n1")
    kv = independent.tuple_
    # read of missing key -> fail not-found
    out = c.invoke({}, {"type": "invoke", "f": "read", "value": kv(1, None)})
    assert out["type"] == "fail" and out["error"] == "not-found"
    # write then read
    assert c.invoke({}, {"type": "invoke", "f": "write",
                         "value": kv(1, 3)})["type"] == "ok"
    out = c.invoke({}, {"type": "invoke", "f": "read", "value": kv(1, None)})
    assert out["type"] == "ok" and out["value"].value == 3
    # cas success and failure
    assert c.invoke({}, {"type": "invoke", "f": "cas",
                         "value": kv(1, [3, 4])})["type"] == "ok"
    assert c.invoke({}, {"type": "invoke", "f": "cas",
                         "value": kv(1, [3, 5])})["type"] == "fail"
    # connection refused -> info for writes, fail for reads
    monkeypatch.setattr(etcd, "client_url",
                        lambda node: "http://127.0.0.1:1")
    c2 = etcd.EtcdClient(timeout=0.2).open({}, "n1")
    assert c2.invoke({}, {"type": "invoke", "f": "write",
                          "value": kv(1, 1)})["type"] == "info"
    assert c2.invoke({}, {"type": "invoke", "f": "read",
                          "value": kv(1, None)})["type"] == "fail"


def test_etcd_test_map():
    t = etcd.etcd_test({"time-limit": 5})
    assert t["name"] == "etcd"
    assert t["db"] is not None and t["client"] is not None
    assert t["generator"] is not None


# --------------------------------------------------------------------------
# full matrix run with an in-process client (monotonic workload)
# --------------------------------------------------------------------------

def test_monotonic_workload_full_run(tmp_path):
    counter = {"v": 0}
    lock = threading.Lock()

    class CounterClient(jclient.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op):
            with lock:
                if op["f"] == "inc":
                    counter["v"] += 1
                    return {**op, "type": "ok", "value": counter["v"]}
                return {**op, "type": "ok", "value": counter["v"]}

    t = suite_test("itest", "monotonic",
                   base_opts(nodes=["n1"], concurrency=4,
                             **{"time-limit": 2}),
                   standard_workloads(),
                   db=None, client=CounterClient())
    t.update({"ssh": {"dummy": True}, "net": jnet.noop(),
              "store": Store(tmp_path / "store"),
              "generator": gen.clients(gen.limit(
                  300, standard_workloads()["monotonic"]()["generator"]))})
    from jepsen_tpu import db as jdb
    t["db"] = jdb.noop()
    t = core.run(t)
    assert t["results"]["valid?"] is True
    assert t["results"]["error-count"] == 0


def test_monotonic_checker_catches_regression():
    from jepsen_tpu.workloads import monotonic
    h = [
        {"type": "invoke", "process": 0, "f": "inc", "value": None},
        {"type": "ok", "process": 0, "f": "inc", "value": 5},
        {"type": "invoke", "process": 1, "f": "read", "value": None},
        {"type": "ok", "process": 1, "f": "read", "value": 3},  # regression
    ]
    res = monotonic.checker().check({}, h, {})
    assert res["valid?"] is False
    assert res["errors"][0]["expected-min"] == 5


def test_monotonic_checker_catches_lost_increment():
    from jepsen_tpu.workloads import monotonic
    h = [
        {"type": "invoke", "process": 0, "f": "inc", "value": None},
        {"type": "ok", "process": 0, "f": "inc", "value": 5},
        {"type": "invoke", "process": 1, "f": "inc", "value": None},
        {"type": "ok", "process": 1, "f": "inc", "value": 5},  # lost update
    ]
    res = monotonic.checker().check({}, h, {})
    assert res["valid?"] is False
    # a read equal to the floor is fine
    h[2] = {"type": "invoke", "process": 1, "f": "read", "value": None}
    h[3] = {"type": "ok", "process": 1, "f": "read", "value": 5}
    assert monotonic.checker().check({}, h, {})["valid?"] is True


def test_analyze_uses_stored_workload(tmp_path):
    """`analyze` must re-check with the run's stored workload, not the
    CLI default (review regression)."""
    import argparse
    from jepsen_tpu.suites import resolve_workload
    args = argparse.Namespace(workload=None)
    assert resolve_workload(args, {"workload": "bank"}, "append") == "bank"
    assert resolve_workload(args, {}, "append") == "append"
    args = argparse.Namespace(workload="set")
    assert resolve_workload(args, {"workload": "bank"}, "append") == "set"


def test_suite_test_preserves_stored_run_identity():
    """Stored name/start-time must survive suite_test so analyze writes
    into the original run dir (review regression)."""
    opts = base_opts(**{"start-time": "20200101T000000",
                        "name": "tidb bank", "workload": "bank"})
    t = suite_test("tidb", "bank", opts, standard_workloads())
    assert t["start-time"] == "20200101T000000"
    assert t["name"] == "tidb bank"


def test_etcd_quorum_option():
    t = etcd.etcd_test({"quorum": True})
    assert t["client"].quorum is True
    assert etcd.etcd_test({})["client"].quorum is False


def test_every_suite_test_map_constructs():
    """<name>_test({"ssh": {"dummy": True}}) must build a full test map
    (db/client/nemesis/generator/checker) for every registry suite —
    the constructor smoke the per-suite tests can't cover for all 28."""
    from jepsen_tpu import suites as S

    for name in S.SUITES:
        mod = S.load_suite(name)
        fn_name = f"{name}_test"
        fn = getattr(mod, fn_name, None)
        assert fn is not None, f"{name} has no {fn_name}"
        t = fn({"ssh": {"dummy": True}})
        assert t.get("generator") is not None, name
        assert t.get("checker") is not None, name
        assert t.get("db") is not None, name


def test_cockroach_nemesis_menu():
    from jepsen_tpu.suites import cockroach as c
    t = c.cockroach_test({"ssh": {"dummy": True}, "nemesis": "clock"})
    assert t["nemesis-name"] == "clock"
    import pytest as _pytest
    with _pytest.raises(ValueError, match="unknown nemesis"):
        c.cockroach_test({"ssh": {"dummy": True}, "nemesis": "bogus"})


def test_every_suite_cli_help():
    """Every suite main must parse `test --help` — catches option
    collisions between suite opt_fns and the standard test options."""
    import contextlib
    import importlib
    import io

    from jepsen_tpu import suites as suites_mod
    for name in sorted(suites_mod.SUITES):
        mod = importlib.import_module(f"jepsen_tpu.suites.{name}")
        main = getattr(mod, "main", None)
        assert main is not None, name
        buf = io.StringIO()
        try:
            with contextlib.redirect_stdout(buf), \
                    contextlib.redirect_stderr(buf):
                rc = main(["test", "--help"])
        except SystemExit as e:
            rc = 0 if e.code in (0, None) else e.code
        assert rc == 0, (name, buf.getvalue()[-300:])
