"""In-process fake Dgraph alpha: the HTTP transaction API
(/alter /query /mutate /commit) over an in-memory predicate store with
snapshot-isolation-style write-write conflict detection — enough to run
the dgraph suite's client end-to-end and to exercise the txn
abort-on-conflict path the workloads rely on."""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


class DgraphStore:
    def __init__(self):
        self.nodes: dict[str, dict] = {}      # uid -> {pred: value}
        self.next_uid = 1
        self.next_ts = 1
        # committed write keys: (pred, value) and uid -> commit_ts
        self.commit_log: dict = {}
        self.txns: dict[int, dict] = {}       # start_ts -> state
        self.lock = threading.RLock()

    def new_ts(self) -> int:
        with self.lock:
            ts = self.next_ts
            self.next_ts += 1
            return ts

    def new_uid(self) -> str:
        uid = f"0x{self.next_uid:x}"
        self.next_uid += 1
        return uid

    # -- queries -------------------------------------------------------

    _re_block = re.compile(
        r"(\w+)\s+as\s+var\s*\(func:\s*(\w+)\(([^)]*)\)\)"
        r"|(\w+)\s*\(func:\s*(\w+)\(([^)]*)\)\)\s*\{([^}]*)\}")

    def query(self, dql: str) -> dict:
        data = {}
        with self.lock:
            for m in self._re_block.finditer(dql):
                if m.group(1):        # var block: name as var(func: ...)
                    name, func, args = m.group(1), m.group(2), m.group(3)
                    data["_var_" + name] = [
                        uid for uid, _ in self._match(func, args)]
                else:
                    name, func, args = m.group(4), m.group(5), m.group(6)
                    fields = m.group(7).split()
                    out = []
                    for uid, node in self._match(func, args):
                        item = {}
                        for f in fields:
                            if f == "uid":
                                item["uid"] = uid
                            elif f in node:
                                item[f] = node[f]
                        out.append(item)
                    data[name] = out
        return data

    def _match(self, func: str, args: str):
        if func == "eq":
            pred, val = [a.strip() for a in args.split(",", 1)]
            val = int(val)
            return [(u, n) for u, n in self.nodes.items()
                    if n.get(pred) == val]
        if func == "has":
            pred = args.strip()
            return [(u, n) for u, n in self.nodes.items() if pred in n]
        return []

    # -- mutations -----------------------------------------------------

    def apply_set(self, set_objs: list, var_uids: dict) -> list:
        """Apply under lock; returns the write keys touched. Mirrors
        real dgraph: `uid(u)` with an empty var drops the object
        silently (no node is created)."""
        keys = []
        for obj in set_objs:
            uid = obj.get("uid")
            if uid and uid.startswith("uid("):
                var = uid[4:-1]
                uids = var_uids.get(var, [])
                if not uids:
                    continue  # real dgraph: no-op, not an insert
                uid = uids[0]
            if not uid or uid.startswith("_:"):
                uid = self.new_uid()
            node = self.nodes.setdefault(uid, {})
            keys.append(uid)
            for pred, val in obj.items():
                if pred == "uid":
                    continue
                node[pred] = val
                keys.append((pred, val if not isinstance(val, dict)
                             else str(val)))
        return keys

    def apply_delete(self, del_objs: list, var_uids: dict) -> list:
        """JSON delete mutations: {"uid": u} alone wipes the node (the
        S * * form); {"uid": u, "pred": ...} drops those predicates.
        Returns write keys for conflict detection."""
        keys = []
        for obj in del_objs:
            uid = obj.get("uid")
            if uid and uid.startswith("uid("):
                uids = var_uids.get(uid[4:-1], [])
                if not uids:
                    continue
                uid = uids[0]
            node = self.nodes.get(uid)
            if node is None:
                continue
            keys.append(uid)
            preds = [p for p in obj if p != "uid"]
            if preds:
                for p in preds:
                    if p in node:
                        keys.append((p, node[p]))
                        del node[p]
                if not node:
                    del self.nodes[uid]
            else:
                keys += [(p, v) for p, v in node.items()
                         if not isinstance(v, dict)]
                del self.nodes[uid]
        return keys

    @staticmethod
    def _cond_ok(cond: str | None, var_uids: dict) -> bool:
        if not cond:
            return True
        m = re.match(r"@if\((eq|gt|lt)\(len\((\w+)\),\s*(\d+)\)\)", cond)
        if not m:
            return True
        n = len(var_uids.get(m.group(2), []))
        want = int(m.group(3))
        return {"eq": n == want, "gt": n > want,
                "lt": n < want}[m.group(1)]

    @staticmethod
    def _blocks(body: dict) -> list[tuple]:
        """-> [(cond, set_objs, del_objs)] covering both the
        single-mutation and the multi-block `mutations` upsert forms."""
        if body.get("mutations") is not None:
            return [(mu.get("cond"), mu.get("set") or [],
                     mu.get("delete") or [])
                    for mu in body["mutations"]]
        return [(body.get("cond"), body.get("set") or [],
                 body.get("delete") or [])]

    def mutate_commit_now(self, body: dict) -> None:
        with self.lock:
            var_uids = {}
            if body.get("query"):
                q = self.query(body["query"])
                var_uids = {k[5:]: v for k, v in q.items()
                            if k.startswith("_var_")}
            keys = []
            for cond, set_objs, del_objs in self._blocks(body):
                if self._cond_ok(cond, var_uids):
                    keys += self.apply_set(set_objs, var_uids)
                    keys += self.apply_delete(del_objs, var_uids)
            ts = self.new_ts()
            for k in keys:
                self.commit_log[k] = ts

    def txn_mutate(self, start_ts: int, body: dict) -> None:
        with self.lock:
            st = self.txns.setdefault(start_ts, {"muts": [],
                                                 "reads": []})
            st["muts"].append(body)

    def commit(self, start_ts: int, abort: bool) -> bool:
        """True = committed; False = conflict abort."""
        with self.lock:
            st = self.txns.pop(start_ts, {"muts": []})
            if abort:
                return True
            # predict write keys without applying, to check conflicts
            pending_keys = []
            for body in st["muts"]:
                for _cond, set_objs, del_objs in self._blocks(body):
                    for obj in set_objs + del_objs:
                        uid = obj.get("uid")
                        if uid and not uid.startswith("_:") and \
                                not uid.startswith("uid("):
                            pending_keys.append(uid)
                        for pred, val in obj.items():
                            if pred != "uid":
                                pending_keys.append((pred, val))
            for k in pending_keys:
                if self.commit_log.get(k, 0) > start_ts:
                    return False
            for body in st["muts"]:
                var_uids = {}
                if body.get("query"):
                    q = self.query(body["query"])
                    var_uids = {k[5:]: v for k, v in q.items()
                                if k.startswith("_var_")}
                keys = []
                for cond, set_objs, del_objs in self._blocks(body):
                    if self._cond_ok(cond, var_uids):
                        keys += self.apply_set(set_objs, var_uids)
                        keys += self.apply_delete(del_objs, var_uids)
                ts = self.new_ts()
                for k in keys:
                    self.commit_log[k] = ts
            return True


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_POST(self):
        srv: FakeDgraphServer = self.server.owner  # type: ignore
        store = srv.store
        parsed = urlparse(self.path)
        qs = parse_qs(parsed.query)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        path = parsed.path

        def reply(obj, code=200):
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        if path == "/alter":
            reply({"data": {"code": "Success"}})
            return
        if path == "/query":
            start_ts = int(qs.get("startTs", [0])[0]) or store.new_ts()
            data = {k: v for k, v in store.query(body.decode()).items()
                    if not k.startswith("_var_")}
            reply({"data": data,
                   "extensions": {"txn": {"start_ts": start_ts}}})
            return
        if path == "/mutate":
            mu = json.loads(body or b"{}")
            commit_now = qs.get("commitNow", ["false"])[0] == "true"
            start_ts = int(qs.get("startTs", [0])[0])
            if commit_now or not start_ts:
                store.mutate_commit_now(mu)
                reply({"data": {"code": "Success"},
                       "extensions": {"txn": {"start_ts":
                                              store.new_ts()}}})
            else:
                store.txn_mutate(start_ts, mu)
                reply({"data": {"code": "Success"},
                       "extensions": {"txn": {"start_ts": start_ts,
                                              "keys": ["k"],
                                              "preds": ["p"]}}})
            return
        if path == "/commit":
            start_ts = int(qs.get("startTs", [0])[0])
            abort = qs.get("abort", ["false"])[0] == "true"
            if store.commit(start_ts, abort):
                reply({"data": {"code": "Success"}})
            else:
                reply({"errors": [{"message":
                                   "Transaction has been aborted."
                                   " Please retry",
                                   "extensions":
                                   {"code": "ErrorAborted"}}]},
                      code=409)
            return
        reply({"errors": [{"message": f"no route {path}"}]}, code=404)


class FakeDgraphServer:
    def __init__(self):
        self.store = DgraphStore()
        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self._srv.owner = self
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
