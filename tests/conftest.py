"""Test configuration.

Runs JAX on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; real-chip benchmarks happen in bench.py).
"""

import os

# Opt-in real-hardware tier: JEPSEN_TPU_PLATFORM set to a non-cpu
# platform (`JEPSEN_TPU_PLATFORM=tpu pytest -m tpu` on a TPU host;
# `=axon` where the chip is reached through the tunnel plugin) skips
# the CPU pin so the `tpu`-marked differential suites run on the chip.
ON_HARDWARE = os.environ.get("JEPSEN_TPU_PLATFORM", "") not in ("", "cpu")

if not ON_HARDWARE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Some environments register a TPU plugin regardless of
    # JAX_PLATFORMS; this pin makes jepsen_tpu.devices resolve the
    # virtual CPU mesh.
    os.environ["JEPSEN_TPU_PLATFORM"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon TPU-tunnel plugin (when present) force-updates the
# jax_platforms *config* to "axon,cpu" from sitecustomize, overriding
# the env var — and initializing the axon backend can hang when the
# tunnel is unreachable. Re-pin the config so tests stay on the
# 8-device virtual CPU mesh.
import jax  # noqa: E402

if not ON_HARDWARE and jax.config.jax_platforms != "cpu":
    jax.config.update("jax_platforms", "cpu")

import random

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: CPU-vs-device differential tests meant for real hardware "
        "(run with JEPSEN_TPU_PLATFORM=tpu pytest -m tpu)")


def pytest_collection_modifyitems(config, items):
    """`tpu`-marked tests only run when hardware is opted in; everything
    else is excluded under the hardware tier (one chip, no virtual
    mesh — the CPU-pinned assumptions of the main suite don't hold)."""
    if ON_HARDWARE:
        skip = pytest.mark.skip(reason="hardware tier runs -m tpu only")
        for it in items:
            if "tpu" not in it.keywords:
                it.add_marker(skip)
    else:
        skip = pytest.mark.skip(
            reason="needs real hardware: JEPSEN_TPU_PLATFORM=tpu")
        for it in items:
            if "tpu" in it.keywords:
                it.add_marker(skip)


@pytest.fixture(autouse=True)
def _seed():
    random.seed(42)


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Drop the process-global tracer around every test: tracing stays
    on (the default-on paths are exercised for real), but one test's
    span events never accumulate into the next — a session-long event
    buffer would grow the gen2 GC scan under the deadline-sensitive
    suite e2e tests."""
    from jepsen_tpu import trace
    trace.reset()
    yield
    trace.reset()
