"""Test configuration.

Runs JAX on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; real-chip benchmarks happen in bench.py).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Some environments register a TPU plugin regardless of JAX_PLATFORMS;
# this pin makes jepsen_tpu.devices resolve the virtual CPU mesh.
os.environ["JEPSEN_TPU_PLATFORM"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon TPU-tunnel plugin (when present) force-updates the
# jax_platforms *config* to "axon,cpu" from sitecustomize, overriding
# the env var — and initializing the axon backend can hang when the
# tunnel is unreachable. Re-pin the config so tests stay on the
# 8-device virtual CPU mesh.
import jax  # noqa: E402

if jax.config.jax_platforms != "cpu":
    jax.config.update("jax_platforms", "cpu")

import random

import pytest


@pytest.fixture(autouse=True)
def _seed():
    random.seed(42)
