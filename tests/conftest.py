"""Test configuration.

Runs JAX on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; real-chip benchmarks happen in bench.py).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Some environments register a TPU plugin regardless of JAX_PLATFORMS;
# this pin makes jepsen_tpu.devices resolve the virtual CPU mesh.
os.environ.setdefault("JEPSEN_TPU_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import random

import pytest


@pytest.fixture(autouse=True)
def _seed():
    random.seed(42)
