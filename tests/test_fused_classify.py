"""Fused detect/classify parity: the single-dispatch kernel (detect
closure + lax.cond-gated classification) must agree bit-for-bit with
the unfused chained-closure classify AND with the detect pass's cycle
verdict, across all four anomaly classes and the synthetic corpus
(checker/elle/synth.py). This pins the tentpole contract: a sweep can
run classify=True at the detect rate without verdict drift.
"""

from __future__ import annotations

import numpy as np
import pytest

from jepsen_tpu import parallel
from jepsen_tpu.checker.elle import synth
from jepsen_tpu.checker.elle import kernels as K
from jepsen_tpu.checker.elle.encode import encode_history


def txn(i, p, mops):
    inv = [[m[0], m[1], None if m[0] == "r" else m[2]] for m in mops]
    return [
        {"type": "invoke", "process": p, "f": "txn", "value": inv,
         "time": i * 1000, "index": 2 * i},
        {"type": "ok", "process": p, "f": "txn", "value": mops,
         "time": i * 1000 + 500, "index": 2 * i + 1},
    ]


def hist_g0():
    """ww cycle: t0 and t1 append to two keys in opposite orders, as
    later observed by reads fixing both version orders."""
    h = []
    h += txn(0, 0, [["append", "x", 1], ["append", "y", 2]])
    h += txn(1, 1, [["append", "y", 1], ["append", "x", 2]])
    h += txn(2, 2, [["r", "x", [1, 2]], ["r", "y", [1, 2]]])
    return h


def hist_g1c():
    """wr cycle: two txns read EACH OTHER's appends."""
    h = []
    h += txn(0, 0, [["append", "a", 1], ["r", "b", [1]]])
    h += txn(1, 1, [["append", "b", 1], ["r", "a", [1]]])
    return h


def hist_g_single():
    """rw + ww cycle: t0's read of k1@[] is overwritten by t1, and t1
    ww-precedes t0 on k2. The trailing observer fixes both version
    chains (unobserved appends encode pos -1 and emit no edges)."""
    h = []
    h += txn(0, 0, [["r", "k1", []], ["append", "k2", 2]])
    h += txn(1, 1, [["append", "k1", 1], ["append", "k2", 1]])
    h += txn(2, 2, [["r", "k1", [1]], ["r", "k2", [1, 2]]])
    return h


def hist_g2():
    """Pure rw cycle (write skew): both txns read the empty prefix the
    other then appends to; the observer fixes the version chains."""
    h = []
    h += txn(0, 0, [["r", "p", []], ["append", "q", 1]])
    h += txn(1, 1, [["r", "q", []], ["append", "p", 1]])
    h += txn(2, 2, [["r", "p", [1]], ["r", "q", [1]]])
    return h


ANOMALY_HISTS = {
    "G0": hist_g0,
    "G1c": hist_g1c,
    "G-single": hist_g_single,
    "G2-item": hist_g2,
}


@pytest.mark.parametrize("name", sorted(ANOMALY_HISTS))
def test_fused_matches_unfused_and_detect_per_class(name):
    enc = encode_history(ANOMALY_HISTS[name]())
    encs = [enc]
    fused = parallel.check_bucketed(encs, None, fused=True,
                                    two_pass=False)
    unfused = parallel.check_bucketed(encs, None, fused=False,
                                      two_pass=False)
    detect = parallel.check_bucketed(encs, None, classify=False)
    assert fused == unfused, (name, fused, unfused)
    assert name in fused[0], (name, fused)
    # detect's cycle bit must fire exactly when classify flags exist
    assert bool(detect[0]) == bool(fused[0]), (name, detect, fused)


def test_fused_mixed_batch_parity():
    """One bucket mixing all four anomaly classes with valid histories:
    the cond fires for the bucket, and every history's flags still
    match the unfused kernel exactly."""
    encs = [encode_history(mk()) for mk in ANOMALY_HISTS.values()]
    encs += [synth.synth_encoded_history(96, K=8) for _ in range(4)]
    fused = parallel.check_bucketed(encs, None, fused=True,
                                    two_pass=False)
    unfused = parallel.check_bucketed(encs, None, fused=False,
                                      two_pass=False)
    two_pass = parallel.check_bucketed(encs, None, two_pass=True)
    assert fused == unfused == two_pass
    assert all(f == {} for f in fused[4:])
    assert all(fused[:4])


def test_fused_all_valid_synth_corpus():
    """The synthetic valid corpus classifies to zero flags through the
    fused kernel (the cond's clean branch), matching detect."""
    batch = synth.synth_valid_batch(B=4, T=256, K=16, seed=2)
    shape = batch["shape"]
    args = parallel.shard_batch(None, batch)
    fused = parallel.sharded_check_fn(None, shape, classify=True,
                                      fused=True)
    detect = parallel.sharded_check_fn(None, shape, classify=False)
    f = np.asarray(fused(*args))
    d = np.asarray(detect(*args))
    assert (f == 0).all(), f
    assert (d == 0).all(), d


def test_fused_injected_cycles_flag_identically():
    """synth.inject_g1c positives through the packed-batch kernel:
    fused and unfused flag words must be identical, and the flagged
    rows exactly the injected ones."""
    batch = synth.synth_valid_batch(B=6, T=256, K=8, seed=3)
    bad = np.array([1, 4])
    batch = synth.inject_g1c(batch, bad, K=8)
    shape = batch["shape"]
    args = parallel.shard_batch(None, batch)
    f = np.asarray(parallel.sharded_check_fn(
        None, shape, classify=True, fused=True)(*args))
    u = np.asarray(parallel.sharded_check_fn(
        None, shape, classify=True, fused=False)(*args))
    np.testing.assert_array_equal(f, u)
    assert set(np.nonzero(f)[0].tolist()) == set(bad.tolist())


def test_fused_on_mesh_matches_single_device():
    """The lax.cond + sharded closure combination must survive GSPMD:
    same verdicts through a dp x mp mesh as unsharded."""
    encs = [encode_history(hist_g1c()), encode_history(hist_g2())]
    encs += [synth.synth_encoded_history(96, K=8) for _ in range(6)]
    mesh = parallel.make_mesh()
    sharded = parallel.check_bucketed(encs, mesh, fused=True,
                                      two_pass=False)
    local = parallel.check_bucketed(encs, None, fused=True,
                                    two_pass=False)
    assert sharded == local


def test_env_gate_restores_two_pass_default(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FUSED_CLASSIFY", "0")
    assert not K.fused_classify_enabled()
    calls = []
    orig = parallel.check_bucketed_async

    def spy(encs, mesh=None, **kw):
        calls.append(kw.get("classify", True))
        return orig(encs, mesh, **kw)

    monkeypatch.setattr(parallel, "check_bucketed_async", spy)
    encs = [synth.synth_encoded_history(96, K=8) for _ in range(3)]
    out = parallel.check_bucketed(encs, None)
    assert all(f == {} for f in out)
    # all-valid two-pass: exactly one detect sweep, no classify pass
    assert calls == [False], calls
