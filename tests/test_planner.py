"""The cost-aware dispatch planner (jepsen_tpu/planner.py).

Pins the ISSUE-16 contract: verdicts are byte-identical with the
planner off, on-but-cold, and on-with-a-fitted-model across the
bucketed sweep, the async pipeline, the fold dispatcher, and the
per-key split; every cold-start decision is the bit-exact heuristic
fallback (admission_cost == fold_cost, plan_buckets ==
bucket_by_length); the fit/save/load/corrupt-degrade snapshot
lifecycle; routing goldens on a seeded costdb; the predicted-vs-
measured honesty loop; and the costdb cold-start ergonomics
(typed empty CostTable). All CPU-safe.
"""

from __future__ import annotations

import json
import math
import os

import pytest

from jepsen_tpu import planner, trace
from jepsen_tpu import store as jstore
from jepsen_tpu.parallel import folding

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("JEPSEN_TPU_PLANNER", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_PLANNER_PATH", raising=False)
    planner.deactivate()
    trace.reset()
    yield
    planner.deactivate()
    trace.reset()


def _cost_records(tpads=(128, 256, 512), *, fused=True, scale=1e-3,
                  formulation="xla-int8", provenance="measured"):
    """A synthetic quadratic costdb: device_secs grows as (T/128)²."""
    return [{
        "kernel": {"classify": True, "realtime": False,
                   "process_order": False, "fused": fused},
        "formulation": formulation,
        "geometry": {"B": 8, "n_txns": t, "n_keys": 4},
        "windows": {"dispatches": 4,
                    "device_secs": 4 * (t / 128) ** 2 * scale,
                    "histories": 32, "min_secs": scale},
        "backend": "cpu", "device_kind": "cpu",
        "provenance": provenance,
    } for t in tpads]


def _search_records(tpads=(128, 256, 512)):
    return [{"dir": "r", "checker": "append", "t_pad": t, "n_txns": t,
             "closure_rounds": 3, "ww_edges": t, "wr_edges": t,
             "rw_edges": t // 2, "rt_edges": 0, "proc_edges": t,
             "margin": 1, "scc_max": 1} for t in tpads]


def _encs(n=6, base_T=40):
    from jepsen_tpu.checker.elle import encode as enc_mod
    from jepsen_tpu.checker.elle.synth import synth_append_history
    return [enc_mod.encode_history(
        synth_append_history(T=base_T + 37 * i, K=4, seed=i))
        for i in range(n)]


def _fitted(tpads=(128, 256, 512)):
    plan = planner.fit_plan(_cost_records(tpads),
                            _search_records(tpads))
    assert plan is not None
    return plan


def _install(plan, monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
    pl = planner.Planner(plan, "fit")
    planner._active = pl
    return pl


# ---------------------------------------------------------------------------
# Gates
# ---------------------------------------------------------------------------

class TestGates:
    def test_default_off(self):
        assert planner.enabled() is False
        assert planner.get() is None

    def test_gate_on_yields_cold_planner(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        pl = planner.get()
        assert pl is not None and not pl.modeled
        assert pl.source == "cold"

    def test_planner_path_override(self, tmp_path, monkeypatch):
        assert jstore.plan_path(tmp_path) == tmp_path / "plan.json"
        pinned = tmp_path / "elsewhere" / "pinned.json"
        monkeypatch.setenv("JEPSEN_TPU_PLANNER_PATH", str(pinned))
        assert jstore.plan_path(tmp_path) == pinned


# ---------------------------------------------------------------------------
# Cold start: every lever is the bit-exact heuristic fallback
# ---------------------------------------------------------------------------

class TestColdFallback:
    def test_admission_cost_is_fold_cost_exactly(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        pl = planner.get()
        for n in (1, 7, 100, 128, 129, 1000, 4096, 50_000):
            assert pl.admission_cost(n) == folding.fold_cost(n)

    def test_plan_buckets_is_bucket_by_length_exactly(self, monkeypatch):
        from jepsen_tpu import parallel
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        encs = _encs()
        pl = planner.get()
        got = pl.plan_buckets(encs, budget_cells=1 << 27)
        assert got == parallel.bucket_by_length(
            encs, budget_cells=1 << 27)

    def test_fused_and_split_keep_defaults(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        pl = planner.get()
        assert pl.fused_choice(True) is True
        assert pl.fused_choice(False) is False
        assert pl.split_native(1) is True
        assert pl.split_native(10 ** 9) is True

    def test_every_cold_decision_counts_as_fallback(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        pl = planner.get()
        pl.admission_cost(100)
        pl.fused_choice(True)
        pl.split_native(5)
        md = trace.get_current().metrics_dict()["counters"]
        assert md["planner.decisions"] == 3
        assert md["planner.fallbacks"] == 3


# ---------------------------------------------------------------------------
# Model fit + prediction
# ---------------------------------------------------------------------------

class TestFit:
    def test_empty_tables_fit_none(self):
        assert planner.fit_plan([], []) is None
        assert planner.fit_plan(None, None) is None
        # estimated-only rows with no measured window are unusable too
        bad = [{"kernel": {"classify": True}, "geometry": {},
                "windows": {}}]
        assert planner.fit_plan(bad, []) is None

    def test_fit_recovers_quadratic_scaling(self):
        plan = _fitted()
        p128 = planner.predict_secs(plan, 128)
        p256 = planner.predict_secs(plan, 256)
        p512 = planner.predict_secs(plan, 512)
        assert p128 and p256 and p512
        assert p256 / p128 == pytest.approx(4.0, rel=0.2)
        assert p512 / p128 == pytest.approx(16.0, rel=0.2)

    def test_unseen_strategy_predicts_none(self):
        plan = _fitted()   # classify-only training data
        assert planner.predict_secs(plan, 128, classify=False) is None

    def test_prediction_is_always_finite(self):
        plan = _fitted()
        # absurd extrapolation stays a finite, orderable float
        wild = planner.predict_secs(plan, 1 << 40)
        assert wild is not None and math.isfinite(wild)
        assert wild <= math.exp(5.0)

    def test_plan_carries_provenance_and_overhead(self):
        plan = _fitted()
        assert plan["provenance"] == "measured"
        assert plan["device_kind"] == "cpu"
        assert plan["trained_records"] == 3
        assert plan["overhead_secs"] == pytest.approx(1e-3)
        assert plan["split_min_ops"] == 0


# ---------------------------------------------------------------------------
# plan.json persistence — snapshot protocol
# ---------------------------------------------------------------------------

class TestPersistence:
    def test_save_load_roundtrip(self, tmp_path):
        plan = _fitted()
        p = tmp_path / "plan.json"
        assert planner.save_plan(p, plan) is True
        got = planner.load_plan(p)
        assert got == json.loads(json.dumps(plan))

    def test_missing_and_corrupt_degrade_to_none(self, tmp_path):
        assert planner.load_plan(tmp_path / "absent.json") is None
        p = tmp_path / "plan.json"
        p.write_text("{corrupt")
        assert planner.load_plan(p) is None

    def test_alien_shape_degrades(self, tmp_path):
        p = tmp_path / "plan.json"
        p.write_text(json.dumps([1, 2, 3]))
        assert planner.load_plan(p) is None
        p.write_text(json.dumps({"v": 999, "modes": {}}))
        assert planner.load_plan(p) is None
        p.write_text(json.dumps({"v": 1, "modes": "nope"}))
        assert planner.load_plan(p) is None

    def test_refresh_persists_and_activate_warm_starts(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        plan = planner.refresh(tmp_path, _cost_records(),
                               _search_records())
        assert plan is not None
        assert (tmp_path / "plan.json").is_file()
        planner.deactivate()
        pl = planner.activate(tmp_path)
        assert pl is not None and pl.modeled
        assert pl.source == "plan"
        assert planner.current_plan() == json.loads(
            json.dumps(plan))

    def test_refresh_with_nothing_to_fit_is_a_noop(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        assert planner.refresh(tmp_path, [], []) is None
        assert not (tmp_path / "plan.json").exists()

    def test_activate_gate_off_is_none(self, tmp_path):
        assert planner.activate(tmp_path) is None
        assert planner.get() is None


# ---------------------------------------------------------------------------
# THE invariant: planner decisions never change verdicts
# ---------------------------------------------------------------------------

class TestVerdictParity:
    def test_bucketed_sweep_parity(self, monkeypatch):
        from jepsen_tpu import parallel
        encs = _encs()
        base = json.dumps(parallel.check_bucketed(encs))
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        planner.deactivate()   # cold
        assert json.dumps(parallel.check_bucketed(encs)) == base
        _install(_fitted(), monkeypatch)   # warm
        assert json.dumps(parallel.check_bucketed(encs)) == base

    def test_async_pipeline_parity(self, monkeypatch):
        from jepsen_tpu import parallel
        encs = _encs()
        pv = parallel.check_bucketed_async(encs)
        base = json.dumps(pv.result({}))
        _install(_fitted(), monkeypatch)
        pv = parallel.check_bucketed_async(encs)
        assert json.dumps(pv.result({})) == base

    def test_fold_dispatcher_parity(self, monkeypatch):
        encs = _encs(4)
        base = json.dumps(folding.FoldDispatcher().verdicts(encs))
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        planner.deactivate()
        cold = json.dumps(folding.FoldDispatcher().verdicts(encs))
        assert cold == base
        _install(_fitted(), monkeypatch)
        warm = json.dumps(folding.FoldDispatcher().verdicts(encs))
        assert warm == base

    def test_split_decline_keeps_subhistories_identical(
            self, tmp_path, monkeypatch):
        from jepsen_tpu import independent
        ops = []
        for i in range(20):
            k = i % 3
            ops.append({"type": "invoke", "process": i % 4,
                        "f": "read", "value": [k, None]})
            ops.append({"type": "ok", "process": i % 4,
                        "f": "read", "value": [k, i]})
        p = tmp_path / "h.jsonl"
        p.write_text("\n".join(json.dumps(o) for o in ops) + "\n")
        hist = [json.loads(ln) for ln in p.read_text().splitlines()]
        base = independent.subhistories_path(hist, p)
        plan = _fitted()
        plan["split_min_ops"] = 10 ** 6   # decline native everywhere
        pl = _install(plan, monkeypatch)
        assert pl.split_native(len(hist)) is False
        stats: dict = {}
        got = independent.subhistories_path(hist, p, stats=stats)
        assert list(got) == list(base)
        for k in base:
            assert got[k] == base[k]
        assert stats.get("native", 0) == 0


# ---------------------------------------------------------------------------
# Routing goldens on a seeded model
# ---------------------------------------------------------------------------

def _const_mode(secs):
    """A mode row predicting a constant `secs` at every geometry."""
    return {"coeffs": [math.log(secs), 0.0, 0.0, 0.0], "points": 3,
            "t_pad_min": 128, "t_pad_max": 512}


class TestRoutingGoldens:
    def test_fused_choice_follows_the_cheaper_strategy(
            self, monkeypatch):
        plan = {"v": 1, "device_kind": "cpu", "backend": "cpu",
                "provenance": "measured", "trained_records": 6,
                "modes": {
                    "classify|nort|fused|xla-int8": _const_mode(1e-2),
                    "classify|nort|twopass|xla-int8":
                        _const_mode(1e-3)},
                "analytics": {}, "overhead_secs": 1e-3,
                "split_min_ops": 0}
        pl = _install(plan, monkeypatch)
        # two-pass modeled 10x cheaper: the default flips off
        assert pl.fused_choice(True) is False
        # flip the curves: fused wins
        plan["modes"]["classify|nort|fused|xla-int8"] = \
            _const_mode(1e-4)
        assert pl.fused_choice(False) is True

    def test_fused_choice_needs_both_strategies_measured(
            self, monkeypatch):
        pl = _install(_fitted(), monkeypatch)   # fused-only training
        assert pl.fused_choice(True) is True
        assert pl.fused_choice(False) is False

    def test_admission_cost_preserves_the_cell_unit(self, monkeypatch):
        pl = _install(_fitted(), monkeypatch)
        # a T_pad=128 history costs exactly 128^2 cells by construction
        assert pl.admission_cost(100) == 128 * 128
        # and the quadratic model tracks the proxy's scale elsewhere
        for n in (300, 1000, 4000):
            proxy = folding.fold_cost(n)
            got = pl.admission_cost(n)
            assert got == pytest.approx(proxy, rel=0.1)
            assert got >= 1

    def test_plan_buckets_is_a_partition_within_budget(
            self, monkeypatch):
        from jepsen_tpu import parallel
        encs = _encs(8)
        pl = _install(_fitted(), monkeypatch)
        budget = 1 << 22
        got = pl.plan_buckets(encs, budget_cells=budget)
        flat = sorted(i for b in got for i in b)
        assert flat == list(range(len(encs)))
        base = parallel.bucket_by_length(encs, budget_cells=budget)
        assert len(got) <= len(base)

    def test_geometry_race_prefers_fewer_dispatches_under_overhead(
            self, monkeypatch):
        from jepsen_tpu import parallel

        class E:
            def __init__(self, n):
                self.n = n

        encs = [E(n) for n in (100, 120, 200, 220, 450, 500)]
        plan = _fitted()
        # dispatch overhead dwarfs per-history cost: coarser buckets
        # (fewer dispatches) must win the race
        plan["overhead_secs"] = 10.0
        pl = _install(plan, monkeypatch)
        budget = 1 << 27
        got = pl.plan_buckets(encs, budget_cells=budget)
        candidates = [parallel.bucket_by_length(
            encs, multiple=m, budget_cells=budget)
            for m in planner.GEOMETRY_CANDIDATES]
        assert got in candidates
        assert len(got) == min(len(c) for c in candidates)


# ---------------------------------------------------------------------------
# Predicted-vs-measured honesty loop + report section
# ---------------------------------------------------------------------------

class TestScoreAndReport:
    def test_score_against_fresh_records(self, monkeypatch):
        pl = _install(_fitted(), monkeypatch)
        err = pl.score_against(_cost_records())
        assert err is not None
        assert err["records"] == 3
        assert 0.0 <= err["mean_rel_err"] <= err["max_rel_err"]
        assert err["mean_rel_err"] < 0.5   # it trained on these
        md = trace.get_current().metrics_dict()
        assert md["counters"]["planner.pred_checked"] == 3
        assert "planner.pred_err_permille" in md["gauges"]

    def test_score_cold_or_alien_records_is_none(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        assert planner.get().score_against(_cost_records()) is None
        pl = _install(_fitted(), monkeypatch)
        assert pl.score_against([]) is None
        assert pl.score_against([{"windows": {}}, "junk"]) is None

    def test_section_and_markdown(self, monkeypatch):
        pl = _install(_fitted(), monkeypatch)
        pl.admission_cost(100)
        pl.fused_choice(True)
        sec = planner.planner_section(pl.plan,
                                      cost_records=_cost_records(),
                                      metrics=trace.get_current().metrics_dict())
        assert sec["enabled"] and sec["modeled"]
        assert sec["decisions"] >= 2
        assert sec["levers"].get("admission") == 1
        assert "classify|nort|fused|xla-int8" in sec["modes"]
        assert sec["predicted_vs_measured"]["records"] == 3
        md = planner.render_planner_md(sec)
        text = "\n".join(md)
        assert "## Cost-aware planner" in text
        # mode keys embed literal pipes — they must arrive escaped so
        # the markdown table keeps its column count
        assert "classify\\|nort\\|fused\\|xla-int8" in text

    def test_cold_section_renders(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PLANNER", "1")
        planner.get().admission_cost(64)
        sec = planner.planner_section(None,
                                      metrics=trace.get_current().metrics_dict())
        assert sec["modeled"] is False
        text = "\n".join(planner.render_planner_md(sec))
        assert "cold start" in text


# ---------------------------------------------------------------------------
# Costdb cold-start ergonomics
# ---------------------------------------------------------------------------

class TestCostTable:
    def test_missing_file_yields_typed_empty_table(self, tmp_path):
        t = jstore.load_costdb(tmp_path / "absent.jsonl")
        assert isinstance(t, list) and list(t) == []
        assert t.exists is False and t.empty is True

    def test_present_table_reports_itself(self, tmp_path):
        p = tmp_path / "costdb.jsonl"
        recs = _cost_records((128,))
        p.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        t = jstore.load_costdb(p)
        assert t.exists is True and t.empty is False
        assert len(t) == 1

    def test_merge_tolerates_absent_shards(self, tmp_path):
        from jepsen_tpu import mesh
        base = tmp_path
        shard0 = jstore.costdb_path(base, shard=0)
        shard0.parent.mkdir(parents=True, exist_ok=True)
        recs = _cost_records((128, 256))
        shard0.write_text(
            "\n".join(json.dumps(r) for r in recs) + "\n")
        # shard 1 never wrote a file — merging the partial fleet works
        merged = mesh.merge_costdbs(base, 2)
        assert len(merged) == 2
        assert jstore.costdb_path(base).is_file()
