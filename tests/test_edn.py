"""EDN codec tests, including round-trips of reference-shaped op maps."""

from jepsen_tpu import edn
from jepsen_tpu.edn import Keyword, Symbol, Tagged


def test_scalars():
    assert edn.loads("nil") is None
    assert edn.loads("true") is True
    assert edn.loads("false") is False
    assert edn.loads("42") == 42
    assert edn.loads("-17") == -17
    assert edn.loads("3.14") == 3.14
    assert edn.loads("1e3") == 1000.0
    assert edn.loads("42N") == 42
    assert edn.loads('"hi\\nthere"') == "hi\nthere"
    assert edn.loads("\\a") == "a"
    assert edn.loads("\\newline") == "\n"


def test_keywords_and_symbols():
    k = edn.loads(":ok")
    assert isinstance(k, Keyword)
    assert k == "ok"  # str-subclass equality
    assert edn.loads(":jepsen.history/op") == "jepsen.history/op"
    s = edn.loads("foo/bar")
    assert isinstance(s, Symbol)
    assert s == "foo/bar"


def test_collections():
    assert edn.loads("[1 2 3]") == [1, 2, 3]
    assert edn.loads("(1 2 3)") == (1, 2, 3)
    assert edn.loads("{:a 1, :b 2}") == {"a": 1, "b": 2}
    assert edn.loads("#{1 2 3}") == frozenset({1, 2, 3})
    assert edn.loads("[[:r 5 [1 2]] [:append 5 3]]") == [
        ["r", 5, [1, 2]], ["append", 5, 3]]


def test_nested_and_comments():
    text = """
    ; a comment
    {:type :invoke, :f :txn, :value [[:append 1 2]], #_:ignored #_:me
     :process 0, :time 12345}
    """
    v = edn.loads(text)
    assert v == {"type": "invoke", "f": "txn",
                 "value": [["append", 1, 2]], "process": 0, "time": 12345}


def test_tagged_and_records():
    t = edn.loads("#foo [1 2]")
    assert t == Tagged("foo", [1, 2])
    rec = edn.loads("#knossos.model.CASRegister{:value 3}")
    assert rec["value"] == 3
    assert rec["edn/tag"] == "knossos.model.CASRegister"
    inst = edn.loads('#inst "2020-01-01T00:00:00Z"')
    assert inst.year == 2020


def test_loads_all():
    vs = edn.loads_all("{:a 1}\n{:b 2}\n; trailing comment\n")
    assert vs == [{"a": 1}, {"b": 2}]


def test_dumps_roundtrip():
    v = {Keyword("type"): Keyword("ok"), Keyword("value"): [1, None, True,
         "s"], Keyword("nested"): {Keyword("x"): frozenset({1, 2})}}
    s = edn.dumps(v)
    assert edn.loads(s) == {"type": "ok", "value": [1, None, True, "s"],
                            "nested": {"x": frozenset({1, 2})}}


def test_map_with_composite_keys():
    v = edn.loads("{[1 :x] :a}")
    assert v == {(1, "x"): "a"}
