"""FaunaDB wire driver + suite client against the fake server, and the
faunadb suite end-to-end (faunadb/src/jepsen/faunadb/ counterparts)."""

from __future__ import annotations

import pytest

from jepsen_tpu import core, independent, net as jnet
from jepsen_tpu.drivers import DBError, fauna_http as q
from jepsen_tpu.store import Store
from jepsen_tpu.suites import faunadb

from fake_fauna import FakeFaunaServer


def hosts_for(srv):
    return {n: ("127.0.0.1", srv.port)
            for n in ("n1", "n2", "n3", "n4", "n5")}


def test_driver_crud_roundtrip():
    with FakeFaunaServer() as srv:
        c = q.connect("127.0.0.1", srv.port)
        c.query(q.create_class({"name": "test"}))
        assert c.query(q.exists(q.class_("test"))) is True
        ref = q.ref_(q.class_("test"), 1)
        c.query(q.create(ref, {"data": {"register": 3}}))
        doc = c.query(q.get_(ref))
        assert doc["data"]["register"] == 3
        assert isinstance(doc["ref"], q.Ref) and doc["ref"].id == "1"
        c.query(q.update(ref, {"data": {"register": 4}}))
        assert c.query(q.select(["data", "register"], q.get_(ref))) == 4
        with pytest.raises(DBError) as ei:
            c.query(q.get_(q.ref_(q.class_("test"), 99)))
        assert ei.value.code == "instance not found"


def test_driver_abort_rolls_back():
    with FakeFaunaServer() as srv:
        c = q.connect("127.0.0.1", srv.port)
        c.query(q.create_class({"name": "t"}))
        ref = q.ref_(q.class_("t"), 1)
        c.query(q.create(ref, {"data": {"v": 1}}))
        with pytest.raises(DBError) as ei:
            c.query(q.do(q.update(ref, {"data": {"v": 9}}),
                         q.abort("nope")))
        assert ei.value.code == "transaction aborted"
        # the update inside the aborted query must not be visible
        assert c.query(q.select(["data", "v"], q.get_(ref))) == 1


def test_client_register_cas():
    with FakeFaunaServer() as srv:
        test = {"db-hosts": hosts_for(srv)}
        c = faunadb.FaunaClient("register").open(test, "n1")
        kv = independent.tuple_(2, 3)
        assert c.invoke(test, {"type": "invoke", "f": "write",
                               "value": kv, "process": 0})["type"] == "ok"
        r = c.invoke(test, {"type": "invoke", "f": "read",
                            "value": independent.tuple_(2, None),
                            "process": 0})
        assert r["type"] == "ok" and r["value"].value == 3
        ok = c.invoke(test, {"type": "invoke", "f": "cas",
                             "value": independent.tuple_(2, [3, 4]),
                             "process": 0})
        assert ok["type"] == "ok"
        miss = c.invoke(test, {"type": "invoke", "f": "cas",
                               "value": independent.tuple_(2, [3, 5]),
                               "process": 0})
        assert miss["type"] == "fail"
        # unwritten key reads nil
        r0 = c.invoke(test, {"type": "invoke", "f": "read",
                             "value": independent.tuple_(7, None),
                             "process": 0})
        assert r0["type"] == "ok" and r0["value"].value is None


def test_client_set_add_read():
    with FakeFaunaServer() as srv:
        test = {"db-hosts": hosts_for(srv)}
        c = faunadb.FaunaClient("set").open(test, "n1")
        for v in (1, 5, 9):
            assert c.invoke(test, {"type": "invoke", "f": "add",
                                   "value": v,
                                   "process": 0})["type"] == "ok"
        r = c.invoke(test, {"type": "invoke", "f": "read", "value": None,
                            "process": 0})
        assert r["type"] == "ok" and r["value"] == {1, 5, 9}


def test_client_bank_transfer_and_abort():
    with FakeFaunaServer() as srv:
        test = {"db-hosts": hosts_for(srv)}
        c = faunadb.FaunaClient("bank").open(test, "n1")
        r = c.invoke(test, {"type": "invoke", "f": "read", "value": None,
                            "process": 0})
        assert sum(r["value"].values()) == 100
        t = c.invoke(test, {"type": "invoke", "f": "transfer",
                            "process": 0,
                            "value": {"from": 0, "to": 3, "amount": 30}})
        assert t["type"] == "ok"
        # overdraw: bank.clj's abort path -> definite :fail :negative
        bad = c.invoke(test, {"type": "invoke", "f": "transfer",
                              "process": 0,
                              "value": {"from": 3, "to": 0,
                                        "amount": 31}})
        assert bad["type"] == "fail" and bad["error"] == "negative"
        r = c.invoke(test, {"type": "invoke", "f": "read", "value": None,
                            "process": 0})
        assert sum(r["value"].values()) == 100 and r["value"][3] == 30


def test_client_monotonic_inc():
    with FakeFaunaServer() as srv:
        test = {"db-hosts": hosts_for(srv)}
        c = faunadb.FaunaClient("monotonic").open(test, "n1")
        assert c.invoke(test, {"type": "invoke", "f": "read",
                               "value": None,
                               "process": 0})["value"] == 0
        vals = [c.invoke(test, {"type": "invoke", "f": "inc",
                                "value": None, "process": 0})["value"]
                for _ in range(3)]
        assert vals == [1, 2, 3]


def test_client_g2_at_most_one_insert_per_key():
    with FakeFaunaServer() as srv:
        test = {"db-hosts": hosts_for(srv)}
        c = faunadb.FaunaClient("g2").open(test, "n1")
        first = c.invoke(test, {"type": "invoke", "f": "insert",
                                "process": 0,
                                "value": independent.tuple_(1, [5, None])})
        assert first["type"] == "ok"
        second = c.invoke(test, {"type": "invoke", "f": "insert",
                                 "process": 0,
                                 "value": independent.tuple_(
                                     1, [None, 6])})
        assert second["type"] == "fail"
        other = c.invoke(test, {"type": "invoke", "f": "insert",
                                "process": 0,
                                "value": independent.tuple_(2, [None, 7])})
        assert other["type"] == "ok"


def test_faunadb_suite_end_to_end(tmp_path):
    with FakeFaunaServer() as srv:
        opts = {
            "workload": "set",
            "ssh": {"dummy": True}, "time-limit": 1.0,
            "extra": {"net": jnet.noop(),
                      "store": Store(tmp_path / "store")},
            "db-hosts": hosts_for(srv),
        }
        test = faunadb.faunadb_test(opts)
        for k in ("db", "os", "nemesis"):
            test.pop(k, None)
        test = core.run(test)
    r = test["results"]
    assert r["valid?"] is True, r
