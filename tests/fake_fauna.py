"""In-process fake FaunaDB: evaluates the FQL wire-JSON forms the
drivers.fauna_http constructors emit against an in-memory store, with
per-query atomicity (mutation journal rolled back on Abort) — enough to
run the faunadb suite's client end-to-end, including the bank
workload's abort-on-negative path."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Abort(Exception):
    def __init__(self, msg):
        super().__init__(msg)
        self.msg = msg


class BadRequest(Exception):
    def __init__(self, code, msg):
        super().__init__(msg)
        self.code = code
        self.msg = msg


def _ref_json(cls: str, id: str) -> dict:
    return {"@ref": {"id": str(id),
                     "class": {"@ref": {"id": cls,
                                        "class": {"@ref":
                                                  {"id": "classes"}}}}}}


class FaunaStore:
    def __init__(self):
        self.classes: set[str] = set()
        self.indexes: dict[str, dict] = {}
        self.instances: dict[tuple, dict] = {}   # (cls, id) -> data
        self.ts = 0
        self.next_id = 1000
        self.lock = threading.RLock()
        self.journal: list | None = None

    # -- journaling (per-query atomicity) ------------------------------

    def _log(self, key):
        if self.journal is not None:
            old = self.instances.get(key)
            self.journal.append(
                (key, None if old is None else json.loads(
                    json.dumps(old))))

    def run(self, expr):
        with self.lock:
            self.journal = []
            try:
                return self.eval(expr, {})
            except Abort:
                for key, old in reversed(self.journal):
                    if old is None:
                        self.instances.pop(key, None)
                    else:
                        self.instances[key] = old
                raise
            finally:
                self.journal = None

    # -- expression evaluation -----------------------------------------

    def _to_ref(self, v):
        """Evaluated value -> (cls, id) tuple."""
        if isinstance(v, tuple) and v and v[0] == "ref":
            return v[1], v[2]
        raise BadRequest("invalid expression", f"not a ref: {v!r}")

    def _apply(self, f, item):
        """Apply an evaluated lambda to one collection item; multi-param
        lambdas destructure list items positionally."""
        if not (isinstance(f, tuple) and f and f[0] == "lambda"):
            raise BadRequest("invalid expression", f"not a lambda: {f!r}")
        _, params, body, closure = f
        env = dict(closure)
        if isinstance(params, str):
            params = [params]
        if len(params) == 1:
            env[params[0]] = item
        else:
            if not isinstance(item, (list, tuple)) or \
                    len(item) != len(params):
                raise BadRequest("invalid expression",
                                 f"arity {len(params)} vs {item!r}")
            env.update(zip(params, item))
        return self.eval(body, env)

    def _doc(self, cls, id):
        data = self.instances[(cls, str(id))]
        return {"ref": _ref_json(cls, id), "ts": self.ts,
                "data": json.loads(json.dumps(data))}

    def eval(self, x, env):
        if isinstance(x, list):
            return [self.eval(v, env) for v in x]
        if not isinstance(x, dict):
            return x

        if "object" in x:
            return {k: self.eval(v, env) for k, v in x["object"].items()}
        if "var" in x:
            name = x["var"]
            if name not in env:
                raise BadRequest("invalid expression", f"unbound {name}")
            return env[name]
        if "let" in x:
            env = dict(env)
            for k, v in x["let"].items():
                env[k] = self.eval(v, env)
            return self.eval(x["in"], env)
        if "if" in x:
            return self.eval(x["then"] if self.eval(x["if"], env)
                             else x["else"], env)
        if "do" in x:
            out = None
            for e in x["do"]:
                out = self.eval(e, env)
            return out
        if "equals" in x:
            vals = [self.eval(v, env) for v in x["equals"]]
            return all(v == vals[0] for v in vals)
        if "add" in x:
            return sum(self.eval(v, env) for v in x["add"])
        if "subtract" in x:
            vals = [self.eval(v, env) for v in x["subtract"]]
            out = vals[0]
            for v in vals[1:]:
                out -= v
            return out
        if "lt" in x:
            vals = [self.eval(v, env) for v in x["lt"]]
            return all(a < b for a, b in zip(vals, vals[1:]))
        if "and" in x:
            return all(self.eval(v, env) for v in x["and"])
        if "not" in x:
            return not self.eval(x["not"], env)
        if "abort" in x:
            raise Abort(self.eval(x["abort"], env))

        if "create_class" in x:
            params = self.eval(x["create_class"], env)
            self.classes.add(params["name"])
            return {"ref": _ref_json("classes", params["name"])}
        if "create_index" in x:
            params = self.eval(x["create_index"], env)
            src = params["source"]
            if isinstance(src, tuple) and src[0] == "class":
                src = src[1]
            elif isinstance(src, dict) and "class" in src:
                src = src["class"]
            params["source"] = src
            self.indexes[params["name"]] = params
            return {"ref": _ref_json("indexes", params["name"])}

        if "lambda" in x:
            return ("lambda", x["lambda"], x["expr"], dict(env))
        if "map" in x:
            f = self.eval(x["map"], env)
            coll = self.eval(x["collection"], env)
            return [self._apply(f, item) for item in coll]
        if "foreach" in x:
            f = self.eval(x["foreach"], env)
            coll = self.eval(x["collection"], env)
            for item in coll:
                self._apply(f, item)
            return coll
        if "create" in x:
            target = self.eval(x["create"], env)
            if isinstance(target, tuple) and target[0] == "class":
                # auto-generated document id (Create on a class ref)
                self.next_id += 1
                target = ("ref", target[1], str(self.next_id))
            cls, id = self._to_ref(target)
            key = (cls, str(id))
            if key in self.instances:
                raise BadRequest("instance already exists",
                                 "document exists")
            params = self.eval(x.get("params"), env) or {}
            self._log(key)
            self.ts += 1
            self.instances[key] = params.get("data", {})
            return self._doc(cls, id)
        if "update" in x:
            cls, id = self._to_ref(self.eval(x["update"], env))
            key = (cls, str(id))
            if key not in self.instances:
                raise BadRequest("instance not found", "not found")
            params = self.eval(x.get("params"), env) or {}
            self._log(key)
            self.ts += 1
            self.instances[key].update(params.get("data", {}))
            return self._doc(cls, id)
        if "delete" in x:
            cls, id = self._to_ref(self.eval(x["delete"], env))
            key = (cls, str(id))
            if key not in self.instances:
                raise BadRequest("instance not found", "not found")
            self._log(key)
            self.ts += 1
            doc = self._doc(cls, id)
            del self.instances[key]
            return doc
        if "get" in x:
            cls, id = self._to_ref(self.eval(x["get"], env))
            if (cls, str(id)) not in self.instances:
                raise BadRequest("instance not found", "not found")
            return self._doc(cls, id)
        if "exists" in x:
            v = self.eval(x["exists"], env)
            if isinstance(v, tuple):
                if v[0] == "ref":
                    return (v[1], str(v[2])) in self.instances
                if v[0] == "class":
                    return v[1] in self.classes
                if v[0] == "index":
                    return v[1] in self.indexes
            raise BadRequest("invalid expression", f"exists? {v!r}")
        if "select" in x:
            path = self.eval(x["select"], env)
            obj = self.eval(x["from"], env)
            for p in path:
                try:
                    obj = obj[p]
                except (KeyError, IndexError, TypeError):
                    raise BadRequest("value not found",
                                     f"no path {path}")
            return obj
        if "match" in x:
            idx = self.eval(x["match"], env)
            if not (isinstance(idx, tuple) and idx[0] == "index"):
                raise BadRequest("invalid expression", "match wants index")
            terms = [self.eval(t, env) for t in x.get("terms", [])]
            return ("match", idx[1], tuple(terms))
        if "paginate" in x:
            m = self.eval(x["paginate"], env)
            if not (isinstance(m, tuple) and m[0] == "match"):
                raise BadRequest("invalid expression", "paginate wants set")
            _, iname, terms = m
            idx = self.indexes.get(iname)
            if idx is None:
                raise BadRequest("instance not found", f"index {iname}")
            rows = []
            for (cls, id), data in sorted(self.instances.items()):
                if cls != idx["source"]:
                    continue
                if terms:
                    tvals = tuple(
                        self._field(data, t["field"])
                        for t in idx.get("terms", []))
                    if tvals != terms:
                        continue
                if idx.get("values"):
                    vals = [(("ref", cls, id) if v["field"] == ["ref"]
                             else self._field(data, v["field"]))
                            for v in idx["values"]]
                    rows.append(vals[0] if len(vals) == 1 else vals)
                else:
                    rows.append(_ref_json(cls, id))
            size = x.get("size", 64)
            # single page (size bounds tested by the driver's cursor
            # loop terminating on a missing `after`)
            return {"data": rows[:size]}

        if "class" in x and set(x) <= {"class"}:
            return ("class", x["class"])
        if "index" in x and set(x) <= {"index"}:
            return ("index", x["index"])
        if "ref" in x:
            base = self.eval(x["ref"], env)
            if isinstance(base, tuple) and base[0] == "class":
                return ("ref", base[1], str(x.get("id")))
            raise BadRequest("invalid expression", f"ref base {base!r}")
        if "time" in x:
            v = self.eval(x["time"], env)
            if v == "now":
                # a monotonic tagged timestamp (the global txn counter),
                # so multimonotonic reads sort by real commit order
                self.ts += 1
                return {"@ts": f"1970-01-01T00:00:00.{self.ts:09d}Z"}
            return v
        if "at" in x:
            return self.eval(x["expr"], env)
        raise BadRequest("invalid expression", f"unknown form {x!r}")

    @staticmethod
    def _field(data, path):
        obj = {"data": data}
        for p in path:
            obj = obj.get(p) if isinstance(obj, dict) else None
            if obj is None:
                return None
        return obj


class FakeFaunaServer:
    """`with FakeFaunaServer() as srv:` — .port, one shared store."""

    def __init__(self):
        self.store = FaunaStore()
        store = self.store

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    expr = json.loads(self.rfile.read(n))
                except Exception:
                    return self._err(400, "invalid expression", "bad json")
                if not self.headers.get("Authorization", ""). \
                        startswith("Basic "):
                    return self._err(401, "unauthorized", "no secret")
                try:
                    res = store.run(expr)
                except Abort as e:
                    return self._err(400, "transaction aborted", e.msg)
                except BadRequest as e:
                    return self._err(400, e.code, e.msg)
                body = json.dumps({"resource": self._enc(res)}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            @staticmethod
            def _enc(v):
                if isinstance(v, tuple) and v and v[0] == "ref":
                    return _ref_json(v[1], v[2])
                if isinstance(v, (tuple, list)):
                    return [Handler._enc(x) for x in v]
                if isinstance(v, dict):
                    return {k: Handler._enc(x) for k, x in v.items()}
                return v

            def _err(self, status, code, desc):
                body = json.dumps({"errors": [
                    {"code": code, "description": desc}]}).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)

    def __enter__(self):
        self.thread.start()
        return self

    def __exit__(self, *exc):
        self.httpd.shutdown()
        self.httpd.server_close()
        return False
