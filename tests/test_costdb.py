"""The device cost observatory (jepsen_tpu/obs/device.py) + costdb.

Pins the ISSUE-12 contract: per-executable XLA cost/memory capture
joined with measured dispatch windows, the device_kind-keyed peak
table, the costdb.jsonl persistence discipline (flushed lines, torn
tails skipped), the two-shard mesh merge deduplication, the report's
device roofline section, residency gauges in health.json, and the
gate-off invariants — zero new files and byte-identical verdicts.
All CPU-safe.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

import pytest

from jepsen_tpu import store as jstore
from jepsen_tpu import trace
from jepsen_tpu.obs import attribution
from jepsen_tpu.obs import device as device_obs

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(autouse=True)
def _fresh_observatory():
    device_obs.reset()
    trace.reset()
    yield
    device_obs.reset()
    trace.reset()


def _encs(n=4, T=40, K=4):
    from jepsen_tpu.checker.elle import encode as enc_mod
    from jepsen_tpu.checker.elle.synth import synth_append_history
    return [enc_mod.encode_history(synth_append_history(T=T, K=K,
                                                        seed=i))
            for i in range(n)]


def _sweep(encs, mesh=None):
    from jepsen_tpu import parallel
    return parallel.check_bucketed(encs, mesh)


# ---------------------------------------------------------------------------
# Peak table (the hard-coded-MFU-peak fix)
# ---------------------------------------------------------------------------

class TestPeakTable:
    def test_known_kinds_resolve_from_table(self):
        from jepsen_tpu.checker.elle import kernels as K
        v5e = K.device_peak("TPU v5 lite")
        assert v5e["source"] == "table"
        assert v5e["int8_tops"] == 394.0
        assert v5e["bf16_tflops"] == 197.0
        assert v5e["hbm_gbps"] == 819.0
        assert K.device_peak("TPU v4")["bf16_tflops"] == 275.0
        assert K.device_peak("TPU v5p")["int8_tops"] == 918.0

    def test_aliases_and_case(self):
        from jepsen_tpu.checker.elle import kernels as K
        assert K.device_peak("tpu v5e")["int8_tops"] == 394.0
        assert K.device_peak("TPU V6E")["bf16_tflops"] == 918.0

    def test_unknown_kind_falls_back_flagged(self):
        # the documented fallback: v5e values, SOURCE SAYS SO — an
        # assumed peak can never read as a table lookup
        from jepsen_tpu.checker.elle import kernels as K
        row = K.device_peak("cpu")
        assert row["int8_tops"] == 394.0
        assert row["source"].startswith("fallback")
        assert row["device_kind"] == "cpu"
        assert K.device_peak("TPU v99")["source"].startswith("fallback")

    def test_key_layout_pinned_to_residency(self):
        # the observatory parses dispatch_key positionally; this pin
        # fails loudly if residency reorders the tuple
        from jepsen_tpu.checker.elle.kernels import BatchShape
        from jepsen_tpu.parallel.residency import ExecutableResidency
        shape = BatchShape(n_txns=128, n_appends=8, n_reads=8,
                           n_keys=16, max_pos=24)
        kw = {"classify": True, "realtime": False,
              "process_order": False, "fused": True}
        key = ExecutableResidency.dispatch_key(kw, shape, donate=True)
        assert len(key) == len(device_obs._KEY_FIELDS)
        assert key[0] is True and key[6] is True          # classify, donate
        assert key[7] == 16 and key[8] == 24 and key[9] == 128
        assert key == device_obs.dispatch_cost_key(
            kw, shape, single_device=True, donate=True)


# ---------------------------------------------------------------------------
# Capture + join: the golden record shape
# ---------------------------------------------------------------------------

class TestCapture:
    def test_golden_record_shape(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_COSTDB", "1")
        encs = _encs()
        verdicts = _sweep(encs)
        assert len(verdicts) == len(encs)
        recs = device_obs.records()
        assert recs, "no record captured from a compiled dispatch"
        r = recs[0]
        # the golden shape: every published field present
        assert r["v"] == 1
        assert set(r["kernel"]) == {"classify", "realtime",
                                    "process_order", "fused"}
        assert r["formulation"] in ("xla-int8", "xla-bf16",
                                    "pallas-int8", "pallas-bf16")
        g = r["geometry"]
        assert g["B"] >= len(encs) and g["n_txns"] % 128 == 0
        assert set(g) == {"B", "n_txns", "n_keys", "max_pos",
                          "n_appends", "n_reads"}
        assert r["analysis"] in ("compiled", "lowered")
        assert r["cost"]["flops"] > 0
        assert r["cost"]["bytes_accessed"] > 0
        w = r["windows"]
        assert w["dispatches"] >= 1 and w["device_secs"] > 0
        assert w["histories"] >= len(encs)
        assert w["min_secs"] <= w["max_secs"]
        assert r["peak"]["hbm_gbps"] > 0
        # CPU windows are honest host measurements, NOT TPU numbers
        assert r["provenance"] == "estimated"
        assert r["achieved"]["flops_per_sec"] > 0
        assert 0 < r["roofline"]["bandwidth_utilization"]
        json.dumps(r)   # a costdb line must be plain JSON

    def test_capture_dedups_per_geometry(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_COSTDB", "1")
        encs = _encs()
        _sweep(encs)
        n1 = len(device_obs.records())
        _sweep(encs)    # same geometry: windows accumulate, no new rec
        recs = device_obs.records()
        assert len(recs) == n1
        assert recs[0]["windows"]["dispatches"] >= 2

    def test_counter_declared_and_ticks(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_COSTDB", "1")
        tr = trace.fresh_run("costdb-unit", scope="sweep")
        _sweep(_encs())
        assert tr.counter("cost_records").value >= 1
        assert "cost_records" in trace.DECLARED_METRICS["counters"]

    def test_gate_off_captures_nothing(self, monkeypatch):
        monkeypatch.delenv("JEPSEN_TPU_COSTDB", raising=False)
        _sweep(_encs())
        assert device_obs.records() == []
        assert device_obs._pending == {}

    def test_verdicts_identical_gate_on_vs_off(self, monkeypatch):
        encs = _encs(n=6)
        monkeypatch.delenv("JEPSEN_TPU_COSTDB", raising=False)
        off = _sweep(encs)
        monkeypatch.setenv("JEPSEN_TPU_COSTDB", "1")
        on = _sweep(encs)
        assert off == on

    def test_residency_gauges_published(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_COSTDB", "1")
        tr = trace.fresh_run("costdb-gauges", scope="sweep")
        _sweep(_encs())
        assert isinstance(tr.gauge("resident_executables").value, int)
        # all pending windows closed: modeled HBM drains to zero
        assert tr.gauge("hbm_modeled_bytes").value == 0
        for g in ("resident_executables", "hbm_modeled_bytes",
                  "hbm_device_bytes"):
            assert g in trace.DECLARED_METRICS["gauges"]

    def test_health_snapshot_carries_device_section(self, monkeypatch):
        from jepsen_tpu.obs.health import health_snapshot
        monkeypatch.setenv("JEPSEN_TPU_COSTDB", "1")
        tr = trace.fresh_run("costdb-health", scope="sweep")
        _sweep(_encs())
        snap = health_snapshot(tr, seq=1)
        dev = snap["device"]
        assert isinstance(dev["resident_executables"], int)
        assert dev["hbm_modeled_bytes"] == 0
        # null, never absent, when the platform reports no stats
        assert "hbm_device_bytes" in dev


# ---------------------------------------------------------------------------
# costdb.jsonl persistence: flushed lines, torn tails, retention
# ---------------------------------------------------------------------------

class TestCostdbFile:
    def test_append_and_load_roundtrip(self, tmp_path):
        p = tmp_path / "costdb.jsonl"
        recs = [{"v": 1, "geometry": {"B": 1}, "i": i}
                for i in range(3)]
        assert jstore.append_costdb(p, recs) == 3
        assert [r["i"] for r in jstore.load_costdb(p)] == [0, 1, 2]

    def test_torn_tail_skipped_on_load(self, tmp_path):
        p = tmp_path / "costdb.jsonl"
        jstore.append_costdb(p, [{"v": 1, "geometry": {"B": 2},
                                  "ok": True}])
        with open(p, "a") as f:     # a crash mid-append: no newline
            f.write('{"v": 1, "geometry": {"B": 3}, "torn')
        loaded = jstore.load_costdb(p)
        assert len(loaded) == 1 and loaded[0]["ok"] is True

    def test_append_seals_torn_tail_first(self, tmp_path):
        # appending after a line that lost its newline must not merge
        # two records into one unparseable line (the journal rule)
        p = tmp_path / "costdb.jsonl"
        with open(p, "w") as f:
            f.write('{"v": 1, "geometry": {}, "torn": tru')
        jstore.append_costdb(p, [{"v": 1, "geometry": {"B": 1},
                                  "fresh": True}])
        loaded = jstore.load_costdb(p)
        assert len(loaded) == 1 and loaded[0]["fresh"] is True

    def test_non_record_lines_skipped(self, tmp_path):
        p = tmp_path / "costdb.jsonl"
        p.write_text('null\n[]\n{"no_geometry": 1}\n'
                     '{"v": 1, "geometry": {"B": 1}}\n')
        assert len(jstore.load_costdb(p)) == 1

    def test_shard_paths(self, tmp_path):
        assert jstore.costdb_path(tmp_path).name == "costdb.jsonl"
        assert jstore.costdb_path(tmp_path, 3).name \
            == "costdb-shard3.jsonl"

    def test_flush_gate_off_no_file(self, tmp_path, monkeypatch):
        monkeypatch.delenv("JEPSEN_TPU_COSTDB", raising=False)
        assert device_obs.flush(tmp_path / "costdb.jsonl") == 0
        assert not (tmp_path / "costdb.jsonl").exists()


# ---------------------------------------------------------------------------
# The real sweep contract: analyze-store writes (or doesn't) the file
# ---------------------------------------------------------------------------

def _synth_store(tmp_path, n=3):
    from jepsen_tpu.checker.elle.synth import synth_append_history
    from jepsen_tpu.store import Store
    store = Store(tmp_path / "store")
    for i in range(n):
        d = store.base / "costdb" / f"2020010{i + 1}T000000"
        d.mkdir(parents=True)
        hist = synth_append_history(T=40, K=4, seed=i)
        (d / "history.jsonl").write_text(
            "\n".join(json.dumps(o) for o in hist) + "\n")
    return store


class TestAnalyzeStore:
    def test_sweep_writes_provenance_tagged_costdb(self, tmp_path,
                                                   monkeypatch):
        from jepsen_tpu import cli
        monkeypatch.setenv("JEPSEN_TPU_COSTDB", "1")
        store = _synth_store(tmp_path)
        assert cli.analyze_store(store, checker="append") == 0
        recs = jstore.load_costdb(store.base)
        assert len(recs) >= 1    # >=1 record per compiled executable
        for r in recs:
            assert r["provenance"] in ("measured", "estimated")
            assert r["windows"]["dispatches"] >= 1

    def test_report_device_section_from_sweep(self, tmp_path,
                                              monkeypatch):
        from jepsen_tpu import cli
        monkeypatch.setenv("JEPSEN_TPU_COSTDB", "1")
        store = _synth_store(tmp_path)
        assert cli.analyze_store(store, checker="append",
                                 report=True) == 0
        rep = json.loads((store.base / "report.json").read_text())
        dev = rep["device"]
        assert dev["records"] and dev["provenance"] == "estimated"
        row = dev["records"][0]
        assert row["dispatches"] >= 1 and row["device_secs"] > 0
        assert row["flops"] > 0
        md = (store.base / "report.md").read_text()
        assert "Device roofline" in md

    def test_gate_off_zero_new_files(self, tmp_path, monkeypatch):
        from jepsen_tpu import cli
        monkeypatch.delenv("JEPSEN_TPU_COSTDB", raising=False)
        store = _synth_store(tmp_path)
        assert cli.analyze_store(store, checker="append",
                                 report=True) == 0
        assert not (store.base / "costdb.jsonl").exists()
        assert not list(store.base.glob("costdb*.jsonl"))
        rep = json.loads((store.base / "report.json").read_text())
        assert "device" not in rep

    def test_gate_off_overhead_is_sub_microsecond(self, monkeypatch):
        # the <1µs contract: a disabled begin/close pair is a gate
        # read + a dict probe
        monkeypatch.delenv("JEPSEN_TPU_COSTDB", raising=False)
        sentinel = object()
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            device_obs.begin_dispatch(sentinel, {}, None, True, False,
                                      None, None)
            device_obs.close_dispatch(sentinel, t0, 1, None)
        per_pair = (time.perf_counter() - t0) / n
        assert per_pair < 5e-6, f"{per_pair * 1e6:.2f}µs per disabled pair"


# ---------------------------------------------------------------------------
# Two-shard mesh merge: one deduplicated costdb
# ---------------------------------------------------------------------------

class TestMeshMerge:
    def _rec(self, B=8, dispatches=2, secs=0.5, provenance="estimated",
             flops=1e9):
        return {
            "v": 1,
            "kernel": {"classify": True, "realtime": False,
                       "process_order": False, "fused": True},
            "formulation": "xla-int8", "donated": True,
            "geometry": {"B": B, "n_txns": 128, "n_keys": 8,
                         "max_pos": 8, "n_appends": 64, "n_reads": 64},
            "backend": "cpu", "device_kind": "cpu",
            "analysis": "compiled",
            "cost": {"flops": flops, "bytes_accessed": 2e8,
                     "transcendentals": None},
            "memory": None, "argument_bytes_actual": 1024,
            "windows": {"dispatches": dispatches,
                        "device_secs": secs, "min_secs": 0.1,
                        "max_secs": 0.4, "histories": B * dispatches},
            "peak": {"device_kind": "cpu", "source": "fallback",
                     "bf16_tflops": 197.0, "int8_tops": 394.0,
                     "hbm_gbps": 819.0, "hbm_gib": 16.0},
            "provenance": provenance,
            "achieved": {"flops_per_sec": None, "bytes_per_sec": None},
            "roofline": {"flops_utilization": None,
                         "bandwidth_utilization": None},
        }

    def test_merge_dedups_same_executable(self):
        a = self._rec(dispatches=2, secs=0.5)
        b = self._rec(dispatches=3, secs=1.0)
        other = self._rec(B=16, dispatches=1, secs=0.2)
        merged = device_obs.merge_records([[a, other], [b]])
        assert len(merged) == 2
        m = next(r for r in merged if r["geometry"]["B"] == 8)
        w = m["windows"]
        assert w["dispatches"] == 5
        assert w["device_secs"] == pytest.approx(1.5)
        assert w["histories"] == 8 * 5
        assert w["min_secs"] == 0.1 and w["max_secs"] == 0.4
        # the roofline is re-derived over the MERGED windows
        assert m["achieved"]["flops_per_sec"] == pytest.approx(
            5 * 1e9 / 1.5)

    def test_merge_keeps_measured_provenance(self):
        a = self._rec(provenance="measured")
        b = self._rec(provenance="estimated")
        merged = device_obs.merge_records([[a], [b]])
        assert len(merged) == 1
        assert merged[0]["provenance"] == "measured"

    def test_two_shard_file_merge(self, tmp_path):
        from jepsen_tpu import mesh
        base = tmp_path
        jstore.append_costdb(jstore.costdb_path(base, 0),
                             [self._rec(dispatches=1, secs=0.3)])
        jstore.append_costdb(jstore.costdb_path(base, 1),
                             [self._rec(dispatches=2, secs=0.6),
                              self._rec(B=32, dispatches=1, secs=0.1)])
        merged = mesh.merge_costdbs(base, 2)
        assert len(merged) == 2
        on_disk = jstore.load_costdb(base)
        assert len(on_disk) == 2
        m = next(r for r in on_disk if r["geometry"]["B"] == 8)
        assert m["windows"]["dispatches"] == 3
        # repeat merge replaces, never doubles (derived artifact)
        mesh.merge_costdbs(base, 2)
        assert len(jstore.load_costdb(base)) == 2

    def test_merge_no_shard_files_is_noop(self, tmp_path):
        from jepsen_tpu import mesh
        assert mesh.merge_costdbs(tmp_path, 2) == []
        assert not (tmp_path / "costdb.jsonl").exists()


# ---------------------------------------------------------------------------
# The report device section pinned on synthetic records (CPU-safe)
# ---------------------------------------------------------------------------

class TestDeviceSection:
    def test_section_and_md_pinned(self):
        rec = TestMeshMerge()._rec(dispatches=4, secs=2.0)
        rec = device_obs.merge_records([[rec]])[0]   # derive rates
        dev = attribution.device_section([rec])
        assert dev["provenance"] == "estimated"
        row = dev["records"][0]
        assert row["dispatches"] == 4
        assert row["achieved_tflops"] == pytest.approx(
            4 * 1e9 / 2.0 / 1e12, rel=1e-3)
        assert row["achieved_gbps"] == pytest.approx(
            4 * 2e8 / 2.0 / 1e9, rel=1e-3)
        assert row["bandwidth_utilization"] == pytest.approx(
            (4 * 2e8 / 2.0) / (819.0 * 1e9), rel=1e-3)
        md = "\n".join(attribution.render_device_md(dev))
        assert "Device roofline" in md
        assert "B8xT128" in md
        assert "estimated" in md
        # the fallback peak is SURFACED, not silently assumed
        assert "fallback" in md

    def test_empty_records_no_section(self):
        assert attribution.device_section([]) is None
        rep_j, rep_m = None, None  # write_report without records
        # write_report(device_records=None) must not add the section
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            rep_j, rep_m = attribution.write_report(d, [],
                                                    device_records=None)
            rep = json.loads(Path(rep_j).read_text())
            assert "device" not in rep

    def test_bandwidth_share_aggregate(self):
        recs = device_obs.merge_records([[
            TestMeshMerge()._rec(dispatches=4, secs=2.0)]])
        bw = device_obs.bandwidth_share(recs)
        assert bw["provenance"] == "estimated"
        assert bw["achieved_bw_share"] == pytest.approx(
            (4 * 2e8 / 2.0) / (819.0 * 1e9), rel=1e-3)
        assert bw["device_secs"] == pytest.approx(2.0)
        assert device_obs.bandwidth_share([]) is None


# ---------------------------------------------------------------------------
# Crash-sim coverage (the JT-DUR dynamic counterpart, `make
# crash-smoke`): the costdb journal family driven through real
# SIGKILL-mid-write and injected short writes — torn tails must be
# sealed + skipped, and a repeat merge must stay idempotent.
# ---------------------------------------------------------------------------

class TestCostdbCrashSim:
    _rec = TestMeshMerge._rec

    def test_kill_mid_append_crash_seals_and_resumes(self, tmp_path):
        # a REAL kill: the child appends complete records, leaves a
        # torn tail on disk, and SIGKILLs itself mid-"write"
        import signal
        import subprocess
        import sys
        import textwrap
        p = tmp_path / "costdb.jsonl"
        child = textwrap.dedent(f"""
            import json, os, signal
            from jepsen_tpu import store
            p = {str(p)!r}
            store.append_costdb(
                p, [{{"v": 1, "geometry": {{"B": i}}, "i": i}}
                    for i in range(3)])
            with open(p, "a") as f:
                f.write('{{"v": 1, "geometry": {{"B": 9}}, "to')
                f.flush()
                os.kill(os.getpid(), signal.SIGKILL)
        """)
        res = subprocess.run(
            [sys.executable, "-c", child], cwd=str(Path(__file__).parents[1]),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, timeout=120)
        assert res.returncode == -signal.SIGKILL, res.stderr.decode()
        # the torn tail is skipped, the complete records survive
        loaded = jstore.load_costdb(p)
        assert [r["i"] for r in loaded] == [0, 1, 2]
        # the next append seals the torn tail before writing: the new
        # record cannot merge into the dead bytes
        assert jstore.append_costdb(
            p, [{"v": 1, "geometry": {"B": 4}, "i": 3}]) == 1
        loaded = jstore.load_costdb(p)
        assert [r["i"] for r in loaded] == [0, 1, 2, 3]

    def test_short_write_crash_mid_record(self, tmp_path, monkeypatch):
        # the faultfs local injector: the write that exhausts the
        # byte budget lands its prefix (flushed) and raises EIO —
        # the torn tail a full disk or a kill leaves behind
        from jepsen_tpu import faultfs
        p = tmp_path / "costdb.jsonl"
        recs = [{"v": 1, "geometry": {"B": i}, "i": i} for i in range(3)]
        line0 = json.dumps(recs[0]) + "\n"
        real_open = open
        monkeypatch.setattr(
            "builtins.open",
            faultfs.faulty_opener(len(line0) + 11, real_open=real_open))
        # best-effort contract: the injected fault must not raise out
        n = jstore.append_costdb(p, recs)
        monkeypatch.setattr("builtins.open", real_open)
        assert n == 1                       # one record fully landed
        raw = p.read_text()
        assert raw.startswith(line0) and not raw.endswith("\n")
        assert [r["i"] for r in jstore.load_costdb(p)] == [0]
        # recovery: seal + append, nothing merged, nothing doubled
        assert jstore.append_costdb(p, recs[1:]) == 2
        assert [r["i"] for r in jstore.load_costdb(p)] == [0, 1, 2]

    def test_merge_crash_at_publish_is_invisible(self, tmp_path,
                                                 monkeypatch):
        # crash between the merged tmp write and os.replace: the
        # previous costdb.jsonl must survive untouched, and the
        # re-merge (and a repeat merge) must converge byte-identical
        from jepsen_tpu import mesh
        base = tmp_path
        jstore.append_costdb(jstore.costdb_path(base, 0),
                             [self._rec(dispatches=1, secs=0.3)])
        jstore.append_costdb(jstore.costdb_path(base, 1),
                             [self._rec(dispatches=2, secs=0.6)])
        before = mesh.merge_costdbs(base, 2)
        assert len(before) == 1
        first_bytes = jstore.costdb_path(base).read_bytes()
        # another shard lands; the next merge dies at the publish
        jstore.append_costdb(jstore.costdb_path(base, 1),
                             [self._rec(B=32, dispatches=1, secs=0.1)])
        real_replace = os.replace

        def boom(src, dst):
            raise OSError(5, "faultfs: injected crash at publish")

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            mesh.merge_costdbs(base, 2)
        monkeypatch.setattr(os, "replace", real_replace)
        # previous merged file intact, no tmp litter
        assert jstore.costdb_path(base).read_bytes() == first_bytes
        assert [f for f in os.listdir(base) if f.endswith(".tmp")] == []
        # the re-merge converges, and a repeat merge is idempotent
        merged = mesh.merge_costdbs(base, 2)
        assert len(merged) == 2
        once = jstore.costdb_path(base).read_bytes()
        mesh.merge_costdbs(base, 2)
        assert jstore.costdb_path(base).read_bytes() == once
