"""The device kernels are the DEFAULT analysis path (the north star's
`:backend :tpu` flag, jepsen/src/jepsen/checker.clj:188-219): every
checker constructor defaults backend="auto", which resolves to the
device engine when an accelerator is reachable (or JEPSEN_TPU_BACKEND
forces it) and to the CPU oracle otherwise — and a full dummy-remote
etcd run's analyze phase actually routes through the device kernels.

Also covers the detect-then-classify two-pass in the bucketed batch
sweep (the production analyze-store path)."""

import json
import threading
from http.server import HTTPServer

import numpy as np
import pytest

from jepsen_tpu import core, devices, parallel
from jepsen_tpu.checker.elle import synth
from jepsen_tpu.store import Store
from jepsen_tpu.suites import etcd
from tests.test_suites import FakeEtcd


# --------------------------------------------------------------------------
# resolve_backend
# --------------------------------------------------------------------------

def test_resolve_backend_explicit_passthrough(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_BACKEND", "tpu")
    assert devices.resolve_backend("cpu") == "cpu"   # explicit beats env
    assert devices.resolve_backend("tpu") == "tpu"


def test_resolve_backend_auto_no_accelerator(monkeypatch):
    monkeypatch.delenv("JEPSEN_TPU_BACKEND", raising=False)
    # conftest pins the cpu platform: no accelerator reachable
    assert devices.resolve_backend("auto") == "cpu"


def test_resolve_backend_auto_with_accelerator(monkeypatch):
    monkeypatch.delenv("JEPSEN_TPU_BACKEND", raising=False)
    monkeypatch.setattr(devices, "accelerator_available", lambda: True)
    assert devices.resolve_backend("auto") == "tpu"


def test_resolve_backend_env_override(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_BACKEND", "tpu")
    assert devices.resolve_backend("auto") == "tpu"
    monkeypatch.setenv("JEPSEN_TPU_BACKEND", "cpu")
    assert devices.resolve_backend("auto") == "cpu"


@pytest.fixture()
def dead_tunnel(monkeypatch):
    """Simulate round 3's environment: no env pin, a registered device
    plugin whose transport is down. Any in-process jax.devices() would
    wedge forever — modeled here as a hard failure so a regression
    can't hide."""
    import jax
    monkeypatch.delenv("JEPSEN_TPU_BACKEND", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_PLATFORM", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(devices, "_backends_already_alive", lambda: False)
    monkeypatch.setattr(devices, "_probe_result", None)
    monkeypatch.setattr(devices, "_probe_platform", None)
    monkeypatch.setattr(
        devices, "probe_default_backend",
        lambda timeout=None: (False, "backend init hung > 120s"))

    def wedge(*a, **kw):
        raise AssertionError(
            "in-process jax.devices() after a failed probe: this call "
            "wedges forever on a dead tunnel (round-3 regression)")

    monkeypatch.setattr(jax, "devices", wedge)
    yield


def test_auto_resolves_cpu_without_touching_jax(dead_tunnel):
    """VERDICT r3 weak-1: with the tunnel dead, `auto` must resolve to
    the jax-free CPU oracles within the probe timeout — never calling
    jax.devices() in-process."""
    assert devices.device_platform() == "cpu"
    assert devices.accelerator_available() is False
    assert devices.resolve_backend("auto") == "cpu"
    assert "hung" in (devices.backend_error or "")


def test_default_devices_probe_failure_raises(dead_tunnel):
    """default_devices(probe=True) must raise a structured error on a
    dead backend instead of attempting an in-process CPU fallback (the
    fallback itself wedged in round 3)."""
    with pytest.raises(devices.BackendUnavailable):
        devices.default_devices(probe=True)


def test_probe_consulted_even_with_device_platform_pin(dead_tunnel,
                                                      monkeypatch):
    """ADVICE r3: a JAX_PLATFORMS value that mentions a device
    transport (the axon plugin exports "axon,cpu") must NOT skip the
    probe — the transport may be down."""
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    assert devices.device_platform() == "cpu"      # probe failed -> cpu
    assert devices.resolve_backend("auto") == "cpu"
    with pytest.raises(devices.BackendUnavailable):
        devices.default_devices(probe=True)


def test_analyze_store_auto_completes_on_dead_tunnel(dead_tunnel,
                                                     tmp_path, capsys,
                                                     monkeypatch):
    """VERDICT r3 item 3's done-bar: with the tunnel dead (faked wedge
    on any in-process jax.devices), `analyze-store --backend auto` —
    the production default — must complete on the CPU oracles within
    the probe budget, never touching jax."""
    from jepsen_tpu import cli
    from jepsen_tpu.checker.elle.synth import synth_append_history
    from jepsen_tpu.history import history_to_edn
    from jepsen_tpu.store import Store
    monkeypatch.delenv("JEPSEN_TPU_BACKEND", raising=False)
    store = Store(tmp_path / "store")
    for ts, kw in [("20260730T000000", {}),
                   ("20260730T000001", {"g1c": True})]:
        d = store.base / "etcd" / ts
        d.mkdir(parents=True)
        (d / "history.edn").write_text(history_to_edn(
            synth_append_history(T=60, K=6, seed=4, **kw)))
    rc = cli.analyze_store(store, checker="append")
    assert rc == 1          # verdicts rendered, invalid run detected
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    assert [ln["valid?"] for ln in lines] == [True, False]


def test_cpu_only_pin_skips_probe(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_PLATFORM", "cpu")

    def no_probe(timeout=None):
        raise AssertionError("probe must be skipped under a cpu-only pin")

    monkeypatch.setattr(devices, "probe_default_backend", no_probe)
    monkeypatch.setattr(devices, "_backends_already_alive", lambda: False)
    assert devices.device_platform() == "cpu"
    assert devices.resolve_backend("auto") == "cpu"


def test_default_constructors_are_auto():
    from jepsen_tpu import checker as jchecker
    from jepsen_tpu.checker import elle
    from jepsen_tpu.checker.elle import wr
    assert jchecker.linearizable().backend == "auto"
    assert elle.append_checker().backend == "auto"
    assert wr.rw_register_checker().backend == "auto"


# --------------------------------------------------------------------------
# the etcd suite's analyze phase takes the device route
# --------------------------------------------------------------------------

@pytest.fixture()
def fake_etcd():
    FakeEtcd.store = {}
    srv = HTTPServer(("127.0.0.1", 0), FakeEtcd)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    yield srv.server_address[1]
    srv.shutdown()


def test_etcd_dummy_run_analyze_routes_device_kernels(
        tmp_path, fake_etcd, monkeypatch):
    """A dummy-remote etcd run (fake in-process etcd) with the forced
    device backend: the linearizability verdict must come out of the
    dense-bitset device kernel, not the CPU WGL engine."""
    monkeypatch.setenv("JEPSEN_TPU_BACKEND", "tpu")
    monkeypatch.setattr(etcd, "client_url",
                        lambda node: f"http://127.0.0.1:{fake_etcd}")
    from jepsen_tpu.checker.knossos import dense
    batches = []
    orig = dense.check_encoded_dense_batch

    def spy(encs, *a, **kw):
        batches.append(len(encs))
        return orig(encs, *a, **kw)

    monkeypatch.setattr(dense, "check_encoded_dense_batch", spy)

    # short nemesis-interval keeps fault ops inside the window (drain
    # now interrupts in-flight sleeps, so a long interval would merely
    # be a no-op nemesis, not a hang)
    t = etcd.etcd_test({"time-limit": 2, "ops-per-key": 15,
                        "threads-per-key": 2, "nemesis-interval": 1})
    t.update(nodes=["n1", "n2", "n3"], concurrency=2,
             ssh={"dummy": True}, store=Store(tmp_path / "store"))
    t = core.run(t)
    assert t["results"]["valid?"] is True
    assert t["results"]["indep"]["valid?"] is True
    assert sum(batches) > 0, "analyze never reached the device kernel"


# --------------------------------------------------------------------------
# detect-then-classify two-pass
# --------------------------------------------------------------------------

def _encs(n_good: int, n_bad: int, T: int = 96, K: int = 8):
    out = [synth.synth_encoded_history(T, K=K) for _ in range(n_good)]
    out += [synth.synth_encoded_history(T, K=K, inject_cycle=True)
            for _ in range(n_bad)]
    return out


def test_strategies_agree():
    encs = _encs(6, 2)
    fused = parallel.check_bucketed(encs, None)   # default: fused
    two = parallel.check_bucketed(encs, None, two_pass=True)
    one = parallel.check_bucketed(encs, None, two_pass=False,
                                  fused=False)
    assert fused == two == one
    assert all(f == {} for f in fused[:6])
    assert all("G1c" in f for f in fused[6:])


def test_fused_default_is_single_dispatch(monkeypatch):
    """The fused default dispatches each bucket ONCE in classify mode
    (the classification closures stay behind the kernel's lax.cond) —
    no detect pre-pass, no re-dispatch of positives."""
    calls = []
    orig = parallel.sharded_check_fn

    def spy(mesh, shape, **kw):
        calls.append(kw.get("classify"))
        return orig(mesh, shape, **kw)

    monkeypatch.setattr(parallel, "sharded_check_fn", spy)
    out = parallel.check_bucketed(_encs(5, 1), None)
    assert all(f == {} for f in out[:5]) and "G1c" in out[5]
    assert calls == [True], calls


def test_two_pass_all_valid_skips_classify(monkeypatch):
    """With the explicit two-pass strategy an all-valid sweep never
    runs a classify dispatch: every dispatch is detect-mode."""
    calls = []
    orig = parallel.sharded_check_fn

    def spy(mesh, shape, **kw):
        calls.append(kw.get("classify"))
        return orig(mesh, shape, **kw)

    monkeypatch.setattr(parallel, "sharded_check_fn", spy)
    out = parallel.check_bucketed(_encs(5, 0), None, two_pass=True)
    assert all(f == {} for f in out)
    assert calls and not any(calls), calls


def test_analyze_store_backend_cpu_routes_host_oracle(
        tmp_path, monkeypatch, capsys):
    """An explicit --backend cpu (exported as JEPSEN_TPU_BACKEND) must
    run the batch sweep on the host oracle, not the device kernels."""
    from jepsen_tpu import cli
    from jepsen_tpu.checker.elle.synth import synth_append_history
    from jepsen_tpu.history import history_to_edn
    monkeypatch.setenv("JEPSEN_TPU_BACKEND", "cpu")

    def boom(*a, **kw):
        raise AssertionError("device sweep ran under --backend cpu")

    monkeypatch.setattr(parallel, "check_bucketed", boom)
    store = Store(tmp_path / "store")
    for ts, kw in [("20260730T000000", {}),
                   ("20260730T000001", {"g1c": True})]:
        d = store.base / "etcd" / ts
        d.mkdir(parents=True)
        (d / "history.edn").write_text(history_to_edn(
            synth_append_history(T=60, K=6, seed=4, **kw)))
    rc = cli.analyze_store(store, checker="append")
    assert rc == 1
    import json as _json
    lines = [_json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    assert lines[0]["valid?"] is True
    assert lines[1]["valid?"] is False


def test_two_pass_on_mesh():
    mesh = parallel.make_mesh()
    encs = _encs(9, 1)
    out = parallel.check_bucketed(encs, mesh)
    assert all(f == {} for f in out[:9])
    assert "G1c" in out[9]
