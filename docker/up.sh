#!/usr/bin/env bash
# Bring up the 1-control + 5-node development cluster (reference
# harness analogue: docker/up.sh there). Generates the cluster SSH key
# on first run, builds and starts the containers, then opens a shell on
# the control node. Options:
#   --daemon      start and return (no control shell)
#   --down        stop and remove the cluster
#   --test        start, run the SSH integration test tier, tear down
set -euo pipefail

cd "$(dirname "${BASH_SOURCE[0]}")"

COMPOSE_CMD=${COMPOSE_CMD:-"docker compose"}

gen_secret() {
    if [ ! -f secret/id_ed25519 ]; then
        echo "[up.sh] generating cluster ssh key"
        mkdir -p secret
        ssh-keygen -t ed25519 -N "" -q -f secret/id_ed25519
    fi
}

case "${1:-}" in
    --down)
        exec $COMPOSE_CMD down -v
        ;;
    --daemon)
        gen_secret
        $COMPOSE_CMD up -d --build
        echo "[up.sh] cluster up; attach with:"
        echo "  docker exec -it jepsen-tpu-control bash"
        ;;
    --test)
        gen_secret
        $COMPOSE_CMD up -d --build
        trap '$COMPOSE_CMD down -v' EXIT
        docker exec \
            -e JEPSEN_TPU_SSH_NODES=n1,n2,n3,n4,n5 \
            jepsen-tpu-control \
            python -m pytest tests/test_integration_ssh.py -v
        ;;
    *)
        gen_secret
        $COMPOSE_CMD up -d --build
        exec docker exec -it jepsen-tpu-control bash
        ;;
esac
