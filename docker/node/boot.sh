#!/usr/bin/env bash
# Node entrypoint: trust the generated cluster key, then sshd.
set -euo pipefail

if [ -f /run/jepsen-secret/id_ed25519.pub ]; then
    install -m 600 /run/jepsen-secret/id_ed25519.pub \
        /root/.ssh/authorized_keys
fi
exec /usr/sbin/sshd -D -e
