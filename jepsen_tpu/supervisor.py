"""Fault-tolerant sweep supervision: the retry/quarantine policy.

Jepsen's premise is that real systems fail partway through, yet the
analysis pipeline used to be fail-fast end to end: one corrupted
history, one crashed pool worker, or one RESOURCE_EXHAUSTED on a
bucket killed an entire store-wide sweep and threw away every verdict
already computed. At production scale partial failure is the steady
state, and — as Elle stresses — a checker must degrade to "unknown",
never to a false verdict or a dead process. This module holds the
policy the recovery layers share:

  * **Quarantine** — a history that fails encode (worker crash,
    truncated sidecar, parse error) or exhausts its retry budget is
    recorded as a ``{"valid?": "unknown", "error": ...}`` verdict and
    the sweep continues (`Quarantined` sentinel, `quarantine_verdict`).
  * **OOM backdown** — `parallel.check_bucketed_async` catches
    RESOURCE_EXHAUSTED / XlaRuntimeError on dispatch, splits the
    bucket in half and retries at a halved per-slot cell budget,
    recursing to singletons; an oversized singleton quarantines.
  * **Watchdog** — `JEPSEN_TPU_DISPATCH_TIMEOUT_S` (default off)
    bounds each batched device wait: the bucket dispatchers, the dense
    long-history check, and the edge-batch kernel (shared by the wr
    sweep and the condensed path's per-SCC classify stage). One retry,
    then the bucket quarantines (`WatchdogTimeout`).
  * **Self-nemesis** — `JEPSEN_TPU_FAULT_INJECT` (e.g.
    ``encode:0.05,oom:first``) deterministically injects encode
    failures, worker kills, and simulated OOMs so every recovery path
    is exercisable without real faults: the checker gets its own
    nemesis.

``JEPSEN_TPU_STRICT=1`` restores the old fail-fast behavior on every
path (injection still fires — a strict run under the nemesis dies
loudly, which is the point of strict). The one owner exempt from
strict's *process death* is the serve daemon: its fold dispatcher
(`parallel.folding.FoldDispatcher`) catches whatever the strict
ladder re-raises and converts it to per-history `unknown` verdicts
for THAT fold only — a long-lived service degrades a tenant's bucket
share, never its own lifetime (the daemon's analogue of "never a
dead sweep").

Every recovery is tracer-attributed: `quarantined`, `oom_retries`,
`bucket_splits`, `watchdog_timeouts` counters plus "quarantine" spans,
surfaced in metrics.json and the bench JSON artifact — and, since the
live-telemetry layer (jepsen_tpu.obs), each recovery also lands as a
typed flight-recorder event in the store's `events.jsonl`
(quarantine/oom_split/watchdog_fire, emitted at the mechanism sites in
`parallel` and `cli`), so a SIGKILLed sweep still leaves the causal
record these counters only summarize.
"""

from __future__ import annotations

import os
import threading
import zlib

_M = 1_000_000


class InjectedFault(RuntimeError):
    """A fault raised by the self-nemesis (JEPSEN_TPU_FAULT_INJECT)."""


class InjectedOom(RuntimeError):
    """A simulated device OOM ('RESOURCE_EXHAUSTED' is in the message
    so `is_oom_error` classifies it exactly like the real thing)."""


class WatchdogTimeout(RuntimeError):
    """A device dispatch exceeded JEPSEN_TPU_DISPATCH_TIMEOUT_S twice."""


class Quarantined:
    """Per-history sentinel verdict for work the supervisor abandoned:
    flows through `PendingVerdicts.result` / `check_bucketed` in place
    of an anomaly dict; callers render it as a ``valid? unknown``
    verdict (`.verdict()`), never as valid or invalid."""

    __slots__ = ("stage", "error")

    def __init__(self, stage: str, error: str):
        # "encode" | "oom" | "watchdog" | "pack" | "stored" |
        # "dispatch" (the serve daemon's whole-fold isolation:
        # parallel.folding.FoldDispatcher quarantines a failed fold's
        # own histories — a poisoned tenant costs its bucket share,
        # never the daemon)
        self.stage = stage
        self.error = error

    def __repr__(self) -> str:
        return f"Quarantined({self.stage}: {self.error})"

    def verdict(self, checker: str | None = None) -> dict:
        return quarantine_verdict(self.error, self.stage, checker)


def quarantine_verdict(error, stage: str,
                       checker: str | None = None) -> dict:
    """The one shape every quarantine path records: validity is
    *unknown* (exit code 2), never false — an abandoned history is not
    evidence of an anomaly — with the cause preserved for triage."""
    res = {"valid?": "unknown", "error": str(error)[:500],
           "quarantined": stage}
    if checker is not None:
        res["checker"] = checker
    return res


def donate_buffers_enabled() -> bool:
    """One home for the JEPSEN_TPU_DONATE_BUFFERS gate (default on):
    single-device bucket dispatches compile with `donate_argnums` over
    the six packed input tensors, so XLA reuses their HBM for the
    closure scratch instead of allocating fresh — the per-dispatch
    footprint drops by the inputs' size and repeat dispatches cycle
    the same arena. 0 keeps inputs alive across the call (debugging,
    backends where donation misbehaves)."""
    from . import gates
    return gates.get("JEPSEN_TPU_DONATE_BUFFERS")


class DeviceSlotLedger:
    """Accounting for donated device-buffer slots: every donated
    dispatch acquires one slot (its six input buffers now belong to
    XLA) and MUST release it on every exit path — success, watchdog
    quarantine, or OOM backdown. The backdown contract in particular:
    a split bucket's original slot is released BEFORE the halves
    re-plan (each half packs fresh buffers and acquires its own slot),
    so recovery can never leak slots however deep the recursion goes.
    The ledger is bookkeeping, not allocation — XLA frees donated
    buffers itself — but a nonzero `inflight()` after a drained sweep
    means some dispatch path lost track of its buffers, which is
    exactly the class of leak the tests pin to zero. Thread-safe (the
    pack-h2d thread and the dispatcher both touch it); the
    `donate_slots_inflight` gauge mirrors every transition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = 0

    def _gauge(self, v: int) -> None:
        from . import trace
        trace.gauge("donate_slots_inflight").set(v)

    def acquire(self) -> None:
        # gauge published INSIDE the lock: concurrent transitions must
        # not publish stale values out of order (a drained sweep whose
        # last publish lost the race would read nonzero forever)
        with self._lock:
            self._inflight += 1
            self._gauge(self._inflight)

    def release(self) -> None:
        with self._lock:
            # never below zero: a non-donated resolve path calling
            # release must be a no-op, not negative bookkeeping
            self._inflight = max(0, self._inflight - 1)
            self._gauge(self._inflight)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight


#: Process-wide ledger the dispatch layer (parallel) threads through.
slot_ledger = DeviceSlotLedger()


def strict_enabled() -> bool:
    """JEPSEN_TPU_STRICT=1 restores fail-fast: no quarantine, no OOM
    backdown — the first failure raises to the caller (CI bisection,
    debugging a specific corrupt store)."""
    from . import gates
    return gates.get("JEPSEN_TPU_STRICT")


def dispatch_timeout_s() -> float | None:
    """The per-dispatch device watchdog (JEPSEN_TPU_DISPATCH_TIMEOUT_S,
    seconds; unset/empty/<=0 disables — the default, because a healthy
    closure on a huge bucket can legitimately run minutes)."""
    from . import gates
    t = gates.get("JEPSEN_TPU_DISPATCH_TIMEOUT_S")
    return t if t is not None and t > 0 else None


def is_oom_error(e: BaseException) -> bool:
    """Device memory exhaustion, by name and message — jaxlib's
    XlaRuntimeError isn't importable without pulling in the runtime,
    and the RESOURCE_EXHAUSTED status string is the stable part of the
    contract across jax versions (InjectedOom carries it too)."""
    if isinstance(e, InjectedOom):
        return True
    name = type(e).__name__
    msg = str(e)
    return ("XlaRuntimeError" in name and
            ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
             or "out of memory" in msg)) \
        or "RESOURCE_EXHAUSTED" in msg


# ---------------------------------------------------------------------------
# Mesh-fleet survivability: per-shard done markers (analyze-store --mesh).
#
# A multi-host sweep must treat a dead host the way a sweep treats a
# quarantined history: the REST of the fleet's work survives and the
# missing piece is re-assignable, never a dead sweep. Each shard
# writes an atomic `.shard-<k>.done` marker (its exit code + shard
# geometry) when its journal and trace artifacts are final; the
# coordinator polls the markers with a bounded wait
# (JEPSEN_TPU_MESH_WAIT_S) and classifies the still-missing shards as
# LOST — their runs count as `unknown` toward the merged exit code,
# and the operator re-runs just that shard anywhere with
# `JEPSEN_TPU_MESH_SHARD=<k> ... --resume` (the per-shard journal is
# the resume evidence, so the replacement host re-checks nothing the
# dead host already verdicted).
# ---------------------------------------------------------------------------

def shard_done_path(store_base, shard: int):
    from pathlib import Path
    return Path(store_base) / f".shard-{shard}.done"


def mark_shard_start(store_base, shard: int) -> None:
    """Clear this shard's stale done marker (a previous sweep's) so
    the coordinator can't merge against last sweep's completion."""
    try:
        shard_done_path(store_base, shard).unlink(missing_ok=True)
    except OSError:
        pass


def mark_shard_done(store_base, shard: int, payload: dict) -> None:
    """Atomically persist this shard's completion marker (best-effort:
    a read-only store must not turn a finished shard into a crash)."""
    import json

    from . import trace
    try:
        trace.atomic_write_text(shard_done_path(store_base, shard),
                                json.dumps(payload))
    except OSError:
        pass


def load_shard_done(store_base, shard: int) -> dict | None:
    import json
    try:
        v = json.loads(shard_done_path(store_base, shard).read_text())
        return v if isinstance(v, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def wait_for_shards(store_base, shards, timeout_s: float,
                    poll_s: float = 0.25):
    """Poll for the done markers of `shards` until all land or
    `timeout_s` expires. Returns (done: {shard: marker payload},
    lost: [shard, ...]) — lost shards are re-assignable, not fatal."""
    import time

    shards = list(shards)
    deadline = time.monotonic() + max(0.0, float(timeout_s or 0.0))
    done: dict[int, dict] = {}
    while True:
        for k in shards:
            if k not in done:
                p = load_shard_done(store_base, k)
                if p is not None:
                    done[k] = p
        missing = [k for k in shards if k not in done]
        if not missing or time.monotonic() >= deadline:
            return done, missing
        time.sleep(min(poll_s, max(0.01,
                                   deadline - time.monotonic())))


# ---------------------------------------------------------------------------
# Self-nemesis: deterministic fault injection (JEPSEN_TPU_FAULT_INJECT)
# ---------------------------------------------------------------------------
#
# Spec grammar: comma-separated `mode:arg` pairs.
#
#   encode:<rate>   fail encode of the run dirs whose name hashes under
#                   <rate> (0..1) — deterministic per run dir, so the
#                   same histories fail in every process and on every
#                   retry (they exhaust their budget and quarantine).
#   encode:first / encode:<N>
#                   fail the first (N) encodes in each process.
#   kill:<rate|first|N>
#                   same selection, but the POOL WORKER kills itself
#                   with SIGKILL instead of raising — the worker-crash
#                   nemesis. In the parent (serial fallback) it
#                   degrades to an encode fault, never a dead sweep.
#   oom:<first|N>   raise a simulated RESOURCE_EXHAUSTED on the first
#                   (N) bucket dispatches of this process.
#
# State is process-local and rebuilt whenever the env spec changes, so
# tests can monkeypatch the env freely.

class _Injector:
    def __init__(self, spec: str):
        self.spec = spec
        self.modes: dict[str, tuple[str, float]] = {}
        self._lock = threading.Lock()
        self._fired: dict[str, int] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part or ":" not in part:
                continue
            mode, _, arg = part.partition(":")
            mode, arg = mode.strip(), arg.strip()
            if arg == "first":
                self.modes[mode] = ("count", 1)
            else:
                try:
                    v = float(arg)
                except ValueError:
                    continue
                if "." in arg or (0 < v < 1):
                    self.modes[mode] = ("rate", v)
                else:
                    self.modes[mode] = ("count", int(v))

    def selects(self, mode: str, name: str | None = None) -> bool:
        """Does `mode` fire for this event? rate-modes hash `name`
        (deterministic everywhere); count-modes burn one of N
        per-process charges."""
        m = self.modes.get(mode)
        if m is None:
            return False
        kind, arg = m
        if kind == "rate":
            h = zlib.crc32((name or "").encode()) % _M
            return h < int(arg * _M)
        with self._lock:
            if self._fired.get(mode, 0) >= arg:
                return False
            self._fired[mode] = self._fired.get(mode, 0) + 1
            return True


_injector: _Injector | None = None
_inj_lock = threading.Lock()


def _get_injector() -> _Injector | None:
    from . import gates
    spec = gates.get("JEPSEN_TPU_FAULT_INJECT")
    global _injector
    inj = _injector
    if inj is None or inj.spec != spec:
        if not spec:
            _injector = None
            return None
        with _inj_lock:
            inj = _injector
            if inj is None or inj.spec != spec:
                inj = _injector = _Injector(spec)
    return inj


def reset_injection() -> None:
    """Drop per-process injection state (tests re-arm count modes)."""
    global _injector
    _injector = None


def _in_pool_worker() -> bool:
    import multiprocessing as mp
    return mp.parent_process() is not None


def maybe_inject_encode_fault(run_dir) -> None:
    """The encode-side nemesis hook (called at the top of
    `ingest.encode_run_dir`): raises InjectedFault, or SIGKILLs the
    current POOL WORKER for kill-mode (in the parent, kill degrades to
    a raise — the nemesis must never kill the sweep itself). Each
    injection leaves an instant mark on the CURRENT tracer — in a
    pool worker that is the worker's own tracer, so the merged sweep
    trace shows the fault on the process it actually hit; kill-mode
    flushes the worker spool first, because a SIGKILLed process gets
    no second chance to write its own post-mortem."""
    inj = _get_injector()
    if inj is None:
        return
    name = os.path.basename(str(run_dir).rstrip("/"))
    if inj.selects("kill", name):
        from . import trace
        trace.get_current().instant("fault_inject", kind="kill",
                                    run=name)
        if _in_pool_worker():
            trace.flush_worker_spool()
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        raise InjectedFault(f"injected worker kill for {name!r} "
                            "(parent process: degraded to encode fault)")
    if inj.selects("encode", name):
        from . import trace
        trace.get_current().instant("fault_inject", kind="encode",
                                    run=name)
        raise InjectedFault(f"injected encode fault for {name!r}")


def maybe_inject_oom() -> None:
    """The dispatch-side nemesis hook (called just before each bucket's
    kernel enqueue in `parallel`)."""
    inj = _get_injector()
    if inj is None:
        return
    if inj.selects("oom"):
        raise InjectedOom("RESOURCE_EXHAUSTED: injected device OOM "
                          "(JEPSEN_TPU_FAULT_INJECT)")
