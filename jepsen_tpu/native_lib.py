"""ctypes loader for the native graph kernels (native/graph_algo.cc).

The C++ library plays the role the JVM's Tarjan-over-bifurcan plays in
the reference's Elle (SURVEY.md §2.3-2.4): a sequential host fallback for
pathological dependency graphs that resist the vectorized/TPU closure
formulation. Compiled on first use with g++ (cached under native/build/);
everything degrades cleanly to the pure-Python implementations when no
toolchain is present.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"

_lock = threading.Lock()


def _compile_so(src: Path, so: Path) -> bool:
    """g++ -> temp file -> atomic rename, so concurrent builders can
    never leave a torn .so for another dlopen. The temp name carries
    pid AND thread id: spawn-pool ingest workers race this across
    processes, and since the build moved outside `_lock`, two threads
    of one process can race it too — a pid-only name would have both
    g++ runs interleaving onto the same file."""
    tmp = so.with_name(
        f".{so.name}.{os.getpid()}.{threading.get_ident()}.tmp")
    try:
        so.parent.mkdir(parents=True, exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-std=c++17", "-shared",
             "-o", str(tmp), str(src)],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native lib build failed (%s): %s", src.name, e)
        tmp.unlink(missing_ok=True)
        return False


def _load_so(src: Path, so: Path) -> ctypes.CDLL | None:
    """Shared build-or-rebuild-then-dlopen recipe: honor the
    JEPSEN_TPU_NO_NATIVE kill switch, rebuild when the source is newer
    than the lib, tolerate a failed rebuild if a stale lib still loads,
    and degrade to None on any failure."""
    from . import gates
    if gates.get("JEPSEN_TPU_NO_NATIVE"):
        return None
    stale = (so.exists() and src.exists()
             and src.stat().st_mtime > so.stat().st_mtime)
    if (not so.exists() or stale) and not (src.exists()
                                           and _compile_so(src, so)):
        if not so.exists():
            return None  # a stale lib still loads; no lib doesn't
    try:
        return ctypes.CDLL(str(so))
    except OSError as e:
        log.debug("native lib load failed (%s): %s", so.name, e)
        return None


_cached: dict[str, ctypes.CDLL | None] = {}

# Fallbacks already warned about (one line per degraded component per
# process; the counter still counts every degraded call).
_warned: set[str] = set()


def count_fallback(what: str) -> None:
    """Count a native→Python degrade in the current tracer's metrics
    without the rebuild-advice warning — for benign per-file declines
    (edited files, content the native pass can't replicate) where the
    library itself is healthy."""
    from . import trace
    trace.counter("native_fallback").inc()
    trace.counter(f"native_fallback.{what}").inc()


def note_fallback(what: str, detail: str = "") -> None:
    """Record a native→Python degrade: bump the `native_fallback`
    metric (plus a per-component counter) and log ONE warning per
    component per process. The native paths otherwise degrade
    silently, which makes a missing/stale .so an invisible 3-9x perf
    regression (ISSUE 2 satellite)."""
    count_fallback(what)
    if what not in _warned:
        _warned.add(what)
        log.warning(
            "native %s unavailable%s; degrading to the Python path "
            "(slower — build with `make -C native` or check g++)",
            what, f" ({detail})" if detail else "")


def _cached_lib(src_name: str, so_name: str, bind) -> ctypes.CDLL | None:
    """One home for the lazy build-load-bind-memoize dance all three
    native libraries share. `bind(L)` attaches restype/argtypes and
    returns False to reject the library (e.g. a stale .so that
    predates the current ABI — it must degrade to the Python engines,
    not crash on missing symbols)."""
    if src_name in _cached:
        L = _cached[src_name]
        if L is None:
            # the warning fired once at first probe, but tracers are
            # per-run: every degraded call still counts, so a later
            # run's metrics.json can't report native_fallback=0 while
            # running fully degraded
            count_fallback(src_name)
        return L
    from . import gates
    # the NO_NATIVE kill switch wins over an explicit lib dir — it
    # must disable EVERY ctypes load, pinned or not
    libdir = None if gates.get("JEPSEN_TPU_NO_NATIVE") \
        else gates.get("JEPSEN_TPU_NATIVE_LIB_DIR")
    # Build + dlopen OUTSIDE the lock: g++ can legitimately run for
    # minutes, and holding the module-wide lock across it would stall
    # every other native consumer (the warm-path hasher included) on
    # an unrelated lib's first build — the JT-LOCK-003 class.
    # _compile_so is temp+rename atomic precisely so concurrent
    # builders (threads here, spawn-pool workers elsewhere) can race
    # harmlessly: at worst the same lib builds twice, never torn.
    if libdir:
        # explicit lib dir (e.g. the sanitizer-instrumented builds):
        # load exactly that lib or degrade to Python — never silently
        # substitute the production build
        try:
            L = ctypes.CDLL(str(Path(libdir) / so_name))
        except OSError as e:
            log.debug("native lib load failed (%s from %s): %s",
                      so_name, libdir, e)
            L = None
    else:
        L = _load_so(_NATIVE_DIR / src_name,
                     _NATIVE_DIR / "build" / so_name)
    if L is not None:
        try:
            if not bind(L):
                L = None
        except AttributeError:
            L = None
    with _lock:
        won = src_name not in _cached
        if won:
            _cached[src_name] = L
        else:
            L = _cached[src_name]   # first finisher won the publish
    if L is None:
        if won:
            note_fallback(
                src_name,
                "JEPSEN_TPU_NO_NATIVE set"
                if gates.get("JEPSEN_TPU_NO_NATIVE")
                else "build/load/ABI-bind failed")
        else:
            count_fallback(src_name)
    return L


def _bind_graph(L: ctypes.CDLL) -> bool:
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    L.jt_tarjan_scc.restype = ctypes.c_int64
    L.jt_tarjan_scc.argtypes = [ctypes.c_int64, i64p, i64p, i64p]
    L.jt_reach.restype = None
    L.jt_reach.argtypes = [ctypes.c_int64, i64p, i64p,
                           ctypes.c_int64, i64p, i64p, u8p]
    return True


def lib() -> ctypes.CDLL | None:
    """The graph-kernel library (Tarjan/BFS), building on first call;
    None when unavailable (no source tree / no compiler)."""
    return _cached_lib("graph_algo.cc", "libjepsen_graph.so",
                       _bind_graph)


def available() -> bool:
    return lib() is not None


# -- history-ingest encoder (native/hist_encode.cc) ----------------------

def _bind_hist(L: ctypes.CDLL) -> bool:
    L.jt_ha_abi_version.restype = ctypes.c_int64
    if L.jt_ha_abi_version() != 5:
        return False
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    L.jt_ha_encode_file.restype = ctypes.c_void_p
    L.jt_ha_encode_file.argtypes = [ctypes.c_char_p]
    L.jt_wr_encode_file.restype = ctypes.c_void_p
    L.jt_wr_encode_file.argtypes = [ctypes.c_char_p]
    L.jt_ha_dims.restype = None
    L.jt_ha_dims.argtypes = [ctypes.c_void_p, i64p]
    for name in ("jt_ha_appends", "jt_ha_reads", "jt_ha_edges",
                 "jt_ha_status", "jt_ha_process", "jt_ha_kid_to_pre"):
        fn = getattr(L, name)
        fn.restype = i32p
        fn.argtypes = [ctypes.c_void_p]
    for name in ("jt_ha_invoke_index", "jt_ha_complete_index",
                 "jt_ha_anomalies"):
        fn = getattr(L, name)
        fn.restype = i64p
        fn.argtypes = [ctypes.c_void_p]
    L.jt_ha_pre_key_names_json.restype = ctypes.c_char_p
    L.jt_ha_pre_key_names_json.argtypes = [ctypes.c_void_p]
    L.jt_ha_free.restype = None
    L.jt_ha_free.argtypes = [ctypes.c_void_p]
    # ABI v5: versioned sidecar writer (1 = lean, 2 = dispatch-shaped)
    # + the bounded-hash primitive (parity-tested against store.xxh64)
    L.jt_ha_write_sidecar.restype = ctypes.c_int64
    L.jt_ha_write_sidecar.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_int64]
    L.jt_xxh64_buf.restype = ctypes.c_uint64
    L.jt_xxh64_buf.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                               ctypes.c_uint64]
    # per-key split (jt_ks_*): same library, own handle type
    L.jt_ks_split_file.restype = ctypes.c_void_p
    L.jt_ks_split_file.argtypes = [ctypes.c_char_p]
    L.jt_ks_dims.restype = None
    L.jt_ks_dims.argtypes = [ctypes.c_void_p, i64p]
    L.jt_ks_key_ids.restype = i32p
    L.jt_ks_key_ids.argtypes = [ctypes.c_void_p]
    L.jt_ks_key_names_json.restype = ctypes.c_char_p
    L.jt_ks_key_names_json.argtypes = [ctypes.c_void_p]
    L.jt_ks_free.restype = None
    L.jt_ks_free.argtypes = [ctypes.c_void_p]
    return True


def hist_lib() -> ctypes.CDLL | None:
    """The native history-ingest encoder (jt_ha_* ABI), built on first
    call; None when unavailable. Same degrade-to-Python contract as
    lib()."""
    return _cached_lib("hist_encode.cc", "libjepsen_histenc.so",
                       _bind_hist)


# -- WGL linearizability search (native/wgl.cc) --------------------------

def _bind_wgl(L: ctypes.CDLL) -> bool:
    L.jt_wgl_abi_version.restype = ctypes.c_int64
    if L.jt_wgl_abi_version() != 2:
        return False
    L.jt_wgl_run.restype = None
    L.jt_wgl_run.argtypes = [ctypes.POINTER(ctypes.c_int32),
                             ctypes.c_int64, ctypes.c_int64,
                             ctypes.c_int64,
                             ctypes.POINTER(ctypes.c_int64)]
    return True


def wgl_lib() -> ctypes.CDLL | None:
    """The native WGL search (jt_wgl_* ABI; CAS-register and mutex
    models), built on first call; None when unavailable — the Python
    engine in checker.knossos stays the oracle and fallback."""
    return _cached_lib("wgl.cc", "libjepsen_wgl.so", _bind_wgl)


def _csr(n: int, adj: list[list[int]]) -> tuple[np.ndarray, np.ndarray] | None:
    """CSR arrays, or None if any column index is out of [0, n) — the
    C++ kernel does no bounds checks, so invalid graphs must take the
    Python path (which raises a clean IndexError instead of corrupting
    memory)."""
    counts = np.fromiter((len(a) for a in adj), np.int64, count=n)
    row_ptr = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=row_ptr[1:])
    col = np.fromiter((w for a in adj for w in a), np.int64,
                      count=int(row_ptr[-1]))
    if col.size and (col.min() < 0 or col.max() >= n):
        return None
    return row_ptr, col


def _p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def tarjan_scc(n: int, adj: list[list[int]]) -> list[int] | None:
    """SCC ids per node via the C++ kernel, or None if unavailable."""
    L = lib()
    if L is None or n == 0:
        return None if L is None else []
    csr = _csr(n, adj)
    if csr is None:
        return None
    row_ptr, col = csr
    out = np.empty(n, np.int64)
    L.jt_tarjan_scc(n, _p(row_ptr), _p(col), _p(out))
    return out.tolist()


def tarjan_scc_csr(n: int, row_ptr: np.ndarray,
                   col: np.ndarray) -> np.ndarray | None:
    """SCC ids straight from CSR arrays (the 100k-node condensation path
    — no Python adjacency lists in between). Returns int64 [n] or None
    when the kernel is unavailable or the CSR is malformed."""
    L = lib()
    if L is None:
        return None
    if n == 0:
        return np.zeros(0, np.int64)
    row_ptr = np.ascontiguousarray(row_ptr, np.int64)
    col = np.ascontiguousarray(col, np.int64)
    if len(row_ptr) != n + 1 or int(row_ptr[-1]) != len(col):
        return None
    if int(row_ptr[0]) != 0 or np.any(np.diff(row_ptr) < 0):
        return None
    if col.size and (col.min() < 0 or col.max() >= n):
        return None
    out = np.empty(n, np.int64)
    L.jt_tarjan_scc(n, _p(row_ptr), _p(col), _p(out))
    return out


def split_key_ids(path) -> tuple[list, np.ndarray] | None:
    """Per-op [key value] split ids for a history.jsonl, from the
    native splitter (hist_encode.cc's jt_ks_* ABI): returns
    (keys, key_ids) where `keys` are the lifted key values in
    first-seen order and `key_ids` is an int32 array aligned with the
    file's op lines (-1 = un-lifted op). None means "use the Python
    splitter" (lib unavailable, file absent, or content whose lift /
    key-equality semantics the native pass can't replicate)."""
    import json

    L = hist_lib()
    if L is None:
        return None
    h = L.jt_ks_split_file(os.fsencode(path))
    if not h:
        # benign: the library is healthy, this file's lift semantics
        # just aren't natively replicable — count, don't cry rebuild
        count_fallback("split_key_ids")
        log.debug("native split declined %s", path)
        return None
    try:
        dims = (ctypes.c_int64 * 4)()
        L.jt_ks_dims(h, dims)
        n_ops, n_keys, json_len, _lifted = dims
        if n_ops == 0:
            ids = np.zeros(0, np.int32)
        else:
            ids = np.ctypeslib.as_array(
                L.jt_ks_key_ids(h), shape=(int(n_ops),)).copy()
        keys = json.loads(
            L.jt_ks_key_names_json(h).decode("utf-8")) if json_len \
            else []
        if len(keys) != int(n_keys):
            note_fallback("split_key_ids", "key-name/ids ABI drift")
            return None  # ABI drift: don't guess
        return keys, ids
    finally:
        L.jt_ks_free(h)


def reach(n: int, adj: list[list[int]],
          queries: list[tuple[int, int]]) -> list[bool] | None:
    """Batch src->dst reachability via the C++ kernel, or None."""
    L = lib()
    if L is None:
        return None
    if not queries:
        return []
    csr = _csr(n, adj)
    if csr is None:
        return None
    row_ptr, col = csr
    src = np.asarray([q[0] for q in queries], np.int64)
    dst = np.asarray([q[1] for q in queries], np.int64)
    out = np.zeros(len(queries), np.uint8)
    L.jt_reach(n, _p(row_ptr), _p(col), len(queries),
               _p(src), _p(dst),
               out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return [bool(x) for x in out]
