"""Small shared utilities.

Counterparts of the reference's jepsen.util (jepsen/src/jepsen/util.clj):
real_pmap (thread-per-element map with exception propagation, util.clj:59),
majority (util.clj:78), relative-time plumbing (util.clj:290-330), retry
loops (util.clj:359), and interval-set rendering (util.clj:548).
"""

from __future__ import annotations

import re as _re
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def majority(n: int) -> int:
    """Smallest majority of n nodes: majority(5) == 3; majority(0) == 1."""
    return n // 2 + 1


def minority(n: int) -> int:
    """Largest strict minority: minority(5) == 2."""
    return max((n - 1) // 2, 0) if n > 0 else 0


def real_pmap(f: Callable[[T], R], coll: Sequence[T]) -> list[R]:
    """Map f over coll with one thread per element, preserving order.
    The first exception raised by any element propagates to the caller
    (all threads are still joined first)."""
    coll = list(coll)
    if not coll:
        return []
    if len(coll) == 1:
        return [f(coll[0])]
    with ThreadPoolExecutor(max_workers=len(coll)) as ex:
        return list(ex.map(f, coll))


def bounded_pmap(f: Callable[[T], R], coll: Sequence[T],
                 max_workers: int | None = None) -> list[R]:
    """Parallel map with a bounded pool (used by the independent checker to
    throttle per-key sub-checks; reference independent.clj:472-492)."""
    import os
    coll = list(coll)
    if not coll:
        return []
    workers = min(len(coll), max_workers or (os.cpu_count() or 4))
    with ThreadPoolExecutor(max_workers=workers) as ex:
        return list(ex.map(f, coll))


# ---------------------------------------------------------------------------
# Relative time: histories are timestamped in nanoseconds from test start.
# ---------------------------------------------------------------------------

_relative_origin = threading.local()


def linear_time_nanos() -> int:
    return _time.monotonic_ns()


class relative_time:
    """Context manager establishing t=0 for the current test run."""

    def __enter__(self):
        _relative_origin.t0 = _time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        _relative_origin.t0 = None
        return False


def relative_time_nanos() -> int:
    t0 = getattr(_relative_origin, "t0", None)
    if t0 is None:
        raise RuntimeError("relative_time_nanos called outside relative_time")
    return _time.monotonic_ns() - t0


def sleep_nanos(dt: int) -> None:
    if dt > 0:
        _time.sleep(dt / 1e9)


_ISO_FRAC = _re.compile(r"[.,](\d+)(?=$|[Z+\-])")


def iso_to_epoch(s: str) -> float:
    """ISO-8601 string -> epoch seconds, preserving FULL fractional
    precision. datetime.fromisoformat silently truncates fractions
    beyond 6 digits (`date -Ins` and Fauna @ts strings carry 9), which
    collapses nanosecond-distinct timestamps onto one microsecond —
    so the fraction is split off and re-added exactly. Comma fractions
    (valid ISO, emitted by `date` in some locales) are handled; naive
    strings are interpreted as LOCAL time, matching the naive producer
    (core.py's start-time)."""
    from datetime import datetime
    frac = 0.0
    m = _ISO_FRAC.search(s)
    if m:
        digits = m.group(1)
        frac = int(digits) / 10 ** len(digits)
        s = s[:m.start()] + s[m.end():]
    s = s.replace("Z", "+00:00")
    return datetime.fromisoformat(s).timestamp() + frac


class RetryFailed(Exception):
    pass


def with_retry(f: Callable[[], R], retries: int = 3, backoff: float = 0.0,
               exceptions: tuple = (Exception,), *,
               exponential: bool = False, fatal: tuple = ()) -> R:
    """Call f, retrying up to `retries` times on the given exceptions.

    With exponential=True each retry sleeps a jittered exponential
    backoff — ``backoff * 2**attempt * uniform(0.5, 1.5)`` (attempt
    counting from 0) — so a herd of workers retrying the same
    transient failure (shm attach, sidecar mmap) decorrelates instead
    of stampeding in lockstep. `fatal` exceptions never retry (e.g. a
    FileNotFoundError under an OSError retry: the segment is gone, not
    busy)."""
    import random
    attempt = 0
    while True:
        try:
            return f()
        except fatal:
            raise
        except exceptions:
            attempt += 1
            if attempt > retries:
                raise
            if backoff:
                delay = backoff
                if exponential:
                    delay = (backoff * 2 ** (attempt - 1)
                             * random.uniform(0.5, 1.5))
                _time.sleep(delay)


def timeout_call(seconds: float, f: Callable[[], R], default: Any = None) -> Any:
    """Run f in a worker thread; return `default` if it takes longer than
    `seconds`; exceptions from f propagate to the caller. (On timeout the
    thread is abandoned, mirroring the reference's util/timeout which
    interrupts; Python threads can't be killed, so callers should make f
    cooperative where it matters. The worker is DAEMONIC — an abandoned
    thread must never hold interpreter exit hostage — and named so a
    faulthandler dump attributes it.)"""
    result: list = []
    error: list = []

    def run():
        try:
            result.append(f())
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            error.append(e)

    t = threading.Thread(target=run, daemon=True, name="timeout-call")
    t.start()
    t.join(seconds)
    if error:
        raise error[0]
    if t.is_alive():
        return default
    return result[0] if result else default


def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Render a set of ints as compact intervals: '#{1..3 5 7..9}'
    (reference util.clj:548 — used in set-full and counter results)."""
    xs = sorted(set(xs))
    if not xs:
        return "#{}"
    parts = []
    lo = hi = xs[0]
    for x in xs[1:]:
        if x == hi + 1:
            hi = x
        else:
            parts.append(f"{lo}" if lo == hi else f"{lo}..{hi}")
            lo = hi = x
    parts.append(f"{lo}" if lo == hi else f"{lo}..{hi}")
    return "#{" + " ".join(parts) + "}"


def longest_common_prefix(seqs: Sequence[Sequence]) -> list:
    """Longest common prefix of several sequences (reference util.clj:703;
    used by set-full's duplicate detection and version-order inference)."""
    if not seqs:
        return []
    shortest = min(seqs, key=len)
    for i, v in enumerate(shortest):
        for s in seqs:
            if s[i] != v:
                return list(shortest[:i])
    return list(shortest)


def pad_to_multiple(xs: Sequence[T], k: int) -> list[T]:
    """xs extended to a multiple of k by replicating its last element —
    the dp-sharding pad for ragged device batches (callers drop the
    replica results past len(xs))."""
    xs = list(xs)
    if k > 1 and xs and len(xs) % k:
        xs += [xs[-1]] * (-len(xs) % k)
    return xs


class LazyAtom:
    """A thread-safe mutable ref whose initial value is computed by
    `f()` on first use; reset bypasses initialization
    (util.clj:730-777). swap applies a function under the lock."""

    _FRESH = object()

    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()
        self._value = LazyAtom._FRESH

    def _init(self):
        if self._value is LazyAtom._FRESH:
            with self._lock:
                if self._value is LazyAtom._FRESH:
                    self._value = self._f()
        return self._value

    def deref(self):
        return self._init()

    def swap(self, f, *args):
        self._init()
        with self._lock:
            self._value = f(self._value, *args)
            return self._value

    def reset(self, v):
        with self._lock:
            self._value = v
            return v


def lazy_atom(f) -> LazyAtom:
    return LazyAtom(f)


def named_locks():
    """A dynamic pool of named locks (util.clj:779-808): call the
    returned function with any hashable name to get the canonical Lock
    for it — e.g. to serialize concurrent daemon restarts per node.
    Use as `with locks(node): ...`."""
    pool: dict = {}
    guard = threading.Lock()

    def lock_for(name):
        with guard:
            if name not in pool:
                pool[name] = threading.Lock()
            return pool[name]

    return lock_for


def chunk_vec(n: int, xs: Sequence[T]) -> list[list[T]]:
    """Split xs into chunks of at most n elements."""
    return [list(xs[i : i + n]) for i in range(0, len(xs), n)]


def name_of(x: Any) -> str:
    """Human-readable name for fs/processes in results."""
    return x if isinstance(x, str) else str(x)


# ---------------------------------------------------------------------------
# Latency pairing and nemesis intervals (reference util.clj:619-700) — the
# data layer under the perf/timeline/clock plot checkers.
# ---------------------------------------------------------------------------

def history_latencies(history: Sequence[dict]) -> list[dict]:
    """Return the history with every invocation annotated with

        "latency"     nanoseconds until its completion
        "completion"  the completing op itself (also latency-annotated)

    Invocations that never complete get neither key. Mirrors the
    reference's jepsen.util/history->latencies (util.clj:619-653)."""
    out: list[dict] = []
    invokes: dict = {}  # process -> index into out
    for op in history:
        if op.get("type") == "invoke":
            out.append(op)
            invokes[op.get("process")] = len(out) - 1
        elif op.get("process") in invokes:
            i = invokes.pop(op.get("process"))
            inv = out[i]
            lat = (op.get("time") or 0) - (inv.get("time") or 0)
            op = {**op, "latency": lat}
            out[i] = {**inv, "latency": lat, "completion": op}
            out.append(op)
        else:
            out.append(op)
    return out


# Nemesis f-names that begin/end a fault window, covering the combined
# nemesis packages' start-x/stop-x convention (nemesis/combined.clj) as
# well as the plain start/stop of nemesis.clj.
DEFAULT_NEMESIS_START_FS = frozenset(
    {"start", "start-partition", "start-kill", "start-pause",
     "kill", "pause"})
DEFAULT_NEMESIS_STOP_FS = frozenset(
    {"stop", "stop-partition", "stop-kill", "stop-pause",
     "resume", "heal", "start!", "stop!"})


def nemesis_intervals(history: Sequence[dict],
                      opts: dict | None = None) -> list[tuple[dict, dict | None]]:
    """Pair nemesis :start/:stop transitions into [start, stop] intervals.

    In runner histories nemesis ops come in invoke/complete pairs with the
    same :f, so ``s1 s2 e1 e2`` pairs the first with the third and the
    second with the fourth (reference util.clj:655-700); a transition
    recorded as a single op (hand-written histories) forms its own event.
    Every open start is closed by the next stop; unclosed starts yield
    (start, None). opts may carry "start"/"stop" f-name sets (defaults
    cover the combined-nemesis start-x/stop-x names)."""
    opts = opts or {}
    start_fs = set(opts.get("start") or DEFAULT_NEMESIS_START_FS)
    stop_fs = set(opts.get("stop") or DEFAULT_NEMESIS_STOP_FS)
    nem = [o for o in history if o.get("process") == "nemesis"]
    # Group invoke/complete pairs (same f, adjacent); lone transitions
    # self-pair.
    events: list[tuple[dict, dict]] = []
    i = 0
    while i < len(nem):
        a = nem[i]
        if i + 1 < len(nem) and nem[i + 1].get("f") == a.get("f"):
            events.append((a, nem[i + 1]))
            i += 2
        else:
            events.append((a, a))
            i += 1
    intervals: list[tuple[dict, dict | None]] = []
    starts: list[tuple[dict, dict]] = []
    for a, b in events:
        f = a.get("f")
        if f in start_fs:
            starts.append((a, b))
        elif f in stop_fs:
            for s1, s2 in starts:
                intervals.append((s1, a))
                if s1 is not s2 or a is not b:
                    intervals.append((s2, b))
            starts = []
    for s1, s2 in starts:
        intervals.append((s1, None))
        if s1 is not s2:
            intervals.append((s2, None))
    return intervals
