"""Independent keys: lift single-key tests over many keys.

Counterpart of jepsen.independent (jepsen/src/jepsen/independent.clj):
op values become `[k v]` tuples; generators run a fresh sub-generator
per key — sequentially (one key at a time) or concurrently (thread
groups each owning a key); the checker splits the history into per-key
subhistories and checks each.

The reference exists because single-history linearizability cost
explodes with length (independent.clj:1-7) and regains throughput with
`bounded-pmap` over keys (independent.clj:472-492). Here the same
decomposition is the TPU *batching* axis: when the sub-checker exposes
`check_batch` (e.g. `checker.linearizable(backend="tpu")`), every
per-key subhistory is encoded into one padded tensor batch and checked
in a single device dispatch, sharded dp across the mesh — keys map to
batch rows instead of JVM threads.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterable

from . import generator as gen
from . import history as h
from . import trace
from .checker import Checker, check_safe, merge_valid
from .util import bounded_pmap

log = logging.getLogger(__name__)


class Tuple(tuple):
    """A distinguished [key value] pair. A subclass so the checker can
    tell lifted values from ordinary two-element vectors
    (independent.clj:22-30)."""

    __slots__ = ()

    def __new__(cls, k, v):
        return super().__new__(cls, (k, v))

    @property
    def key(self):
        return self[0]

    @property
    def value(self):
        return self[1]

    def __repr__(self):
        return f"[{self[0]!r} {self[1]!r}]"


def tuple_(k, v) -> Tuple:
    return Tuple(k, v)


def is_tuple(v: Any) -> bool:
    return isinstance(v, Tuple)


def key_of(v: Any):
    return v.key if is_tuple(v) else None


def relift_history(history: list) -> list:
    """Re-lift [k v] op values into Tuples after a serialization round
    trip that erased the type (history.jsonl / history.edn render a
    tuple as a plain two-element vector; the reference's MapEntry has
    the same ambiguity, which is why its analyze path re-reads
    fressian). Heuristic, applied only when unambiguous: every client
    op value that isn't None must be a two-element list AND at least
    one ok read's value must be one too (an UNlifted register history
    has scalar read values, so it never matches; an unlifted cas-only
    history is ambiguous and stays unlifted)."""
    if any(is_tuple(o.get("value")) for o in history):
        return history
    client = [o for o in history if o.get("process") != "nemesis"]
    vals = [o.get("value") for o in client if o.get("value") is not None]
    if not vals or not all(isinstance(v, (list, tuple)) and len(v) == 2
                           for v in vals):
        return history
    if not any(o.get("type") == "ok" and o.get("f") == "read"
               and isinstance(o.get("value"), (list, tuple))
               for o in client):
        return history
    return [({**o, "value": Tuple(o["value"][0], o["value"][1])}
             if o.get("process") != "nemesis"
             and isinstance(o.get("value"), (list, tuple))
             and len(o["value"]) == 2 else o)
            for o in history]


def value_of(v: Any):
    return v.value if is_tuple(v) else v


def _wrap(k, res):
    """Wrap a sub-generator op result's value into a [k v] tuple.
    Only client invocations are lifted: interpreter pseudo-ops
    (sleep/log) carry scalar payloads the event loop consumes directly
    — a lifted sleep duration would crash the worker."""
    o, g2 = res
    if isinstance(o, dict) and o.get("type") in (None, "invoke"):
        o = {**o, "value": Tuple(k, o.get("value"))}
    return o, g2


def _unwrap_event(event: dict) -> dict:
    v = event.get("value")
    if is_tuple(v):
        return {**event, "value": v.value}
    return event


class SequentialGenerator(gen.Generator):
    """One key at a time: run gen_fn(k) to exhaustion, then the next key
    (independent.clj:32-66)."""

    def __init__(self, keys: Iterable, gen_fn: Callable,
                 _state=None):
        if _state is None:
            keys = list(keys)
            _state = (keys, 0, gen_fn(keys[0]) if keys else None)
        self.gen_fn = gen_fn
        self.keys, self.i, self.cur = _state

    def _with(self, i, cur):
        return SequentialGenerator(
            self.keys, self.gen_fn, _state=(self.keys, i, cur))

    def op(self, test, ctx):
        i, cur = self.i, self.cur
        while i < len(self.keys):
            if cur is None:
                cur = self.gen_fn(self.keys[i])
            res = gen.op(cur, test, ctx)
            if res is not None:
                o, g2 = _wrap(self.keys[i], res)
                return o, self._with(i, g2)
            i, cur = i + 1, None
        return None

    def update(self, test, ctx, event):
        if self.cur is None or self.i >= len(self.keys):
            return self
        v = event.get("value")
        if is_tuple(v) and v.key == self.keys[self.i]:
            return self._with(
                self.i,
                gen.update(self.cur, test, ctx, _unwrap_event(event)))
        return self


def sequential_generator(keys: Iterable, gen_fn: Callable):
    return SequentialGenerator(keys, gen_fn)


class ConcurrentGenerator(gen.Generator):
    """Thread groups of size n, each owning one key at a time
    (independent.clj:138-268, the pure PureConcurrentGenerator).

    Client threads are partitioned by `thread // n`; each group runs
    gen_fn(k) restricted to its own threads and claims the next
    unclaimed key when its current generator is exhausted. Requires
    integer client threads; the nemesis is untouched (wrap with
    gen.clients as usual)."""

    def __init__(self, n: int, keys: Iterable, gen_fn: Callable,
                 _state=None):
        self.n = n
        self.gen_fn = gen_fn
        if _state is None:
            _state = (list(keys), 0, {}, {})
        # groups: group-id -> (key, sub-generator); done groups absent
        # but recorded in exhausted so they don't re-claim.
        self.keys, self.next_key, self.groups, self.key_group = _state

    def _with(self, next_key, groups, key_group):
        return ConcurrentGenerator(
            self.n, self.keys, self.gen_fn,
            _state=(self.keys, next_key, groups, key_group))

    def _group_threads(self, ctx, g):
        lo, hi = g * self.n, (g + 1) * self.n
        return lambda t: isinstance(t, int) and lo <= t < hi

    def _probe(self, g, test, ctx):
        """Try to produce an op from group g against a private copy of
        the state; only the winning probe's state is kept, so key
        claims by losing probes simply re-happen next call (gen.op is
        pure). Returns (op, successor-ConcurrentGenerator) or None."""
        groups = dict(self.groups)
        key_group = dict(self.key_group)
        nk = self.next_key
        gctx = ctx.restrict(self._group_threads(ctx, g))
        entry = groups.get(g)
        while True:
            if entry is None:
                if nk >= len(self.keys):
                    return None
                k = self.keys[nk]
                nk += 1
                entry = (k, self.gen_fn(k))
                key_group[k] = g
            k, sub = entry
            res = gen.op(sub, test, gctx)
            if res is None:
                entry = None
                continue
            o, g2 = _wrap(k, res)
            groups[g] = (k, g2)
            return o, self._with(nk, groups, key_group)

    def op(self, test, ctx):
        soonest = None
        gids = sorted({t // self.n for t in ctx.free_threads
                      if isinstance(t, int)})
        for g in gids:
            cand = self._probe(g, test, ctx)
            if cand is not None:
                soonest = gen.soonest_op_vec(soonest, (*cand, g))
        if soonest is None:
            return None
        o, succ, _ = soonest
        return o, succ

    def update(self, test, ctx, event):
        v = event.get("value")
        if not is_tuple(v):
            return self
        g = self.key_group.get(v.key)
        if g is None or g not in self.groups:
            return self
        k, sub = self.groups[g]
        if k != v.key:
            return self
        gctx = ctx.restrict(self._group_threads(ctx, g))
        groups = dict(self.groups)
        groups[g] = (k, gen.update(sub, test, gctx,
                                   _unwrap_event(event)))
        return self._with(self.next_key, groups, self.key_group)


def concurrent_generator(n: int, keys: Iterable, gen_fn: Callable):
    return ConcurrentGenerator(n, keys, gen_fn)


# ---------------------------------------------------------------------------
# Checker
# ---------------------------------------------------------------------------

def history_keys(history: list) -> list:
    """All keys appearing in lifted op values, in first-seen order
    (independent.clj:426-437)."""
    seen = []
    ss = set()
    for o in history:
        v = o.get("value")
        if is_tuple(v) and v.key not in ss:
            ss.add(v.key)
            seen.append(v.key)
    return seen


def subhistory(k, history: list) -> list:
    """The history restricted to key k: lifted ops for k unwrapped;
    un-lifted ops (nemesis &c) retained (independent.clj:438-449)."""
    out = []
    for o in history:
        v = o.get("value")
        if is_tuple(v):
            if v.key == k:
                out.append({**o, "value": v.value})
        else:
            out.append(o)
    return out


def native_split_enabled() -> bool:
    """One home for the JEPSEN_TPU_NATIVE_SPLIT gate (default on) so
    the register sweep and the bench's reporting can't drift apart:
    `=0` pins the pure-Python relift+subhistories splitter."""
    from . import gates

    return gates.get("JEPSEN_TPU_NATIVE_SPLIT")


def _subhistories_from_ids(history: list, key_ids, keys: list) -> dict:
    """subhistories() driven by a precomputed per-op key-id array (the
    native splitter's output): identical per-key lists, but the per-op
    lift heuristics, Tuple construction and relift dict copies are
    gone — only the one unavoidable value-unwrap copy per lifted op
    remains. `key_ids[i]` is the key id of history[i]'s lifted value
    (-1 un-lifted); `keys` maps ids to key values in first-seen order."""
    subs: dict = {}
    unlifted: list = []
    get = subs.get
    for o, kid in zip(history, key_ids):
        if kid >= 0:
            k = keys[kid]
            lst = get(k)
            if lst is None:
                lst = subs[k] = list(unlifted)
            d = o.copy()
            d["value"] = o["value"][1]
            lst.append(d)
        else:
            unlifted.append(o)
            for lst in subs.values():
                lst.append(o)
    return subs


def subhistories_path(history: list, path, stats: dict | None = None) -> dict:
    """`subhistories(relift_history(history))` for a history loaded
    from `path` (a history.jsonl), accelerated by the native per-key
    splitter (native/hist_encode.cc's jt_ks_* pass) when it applies —
    the register-sweep splitter moved out of the per-op Python loop.
    Falls back to the pure-Python pipeline whenever the native side
    declines the file, the JEPSEN_TPU_NATIVE_SPLIT gate is off, or the
    id array doesn't align with `history` (e.g. the caller loaded a
    different/edited file). `stats`, when given, counts which path
    ACTUALLY ran per call ("native"/"python") so reporters can't
    mistake availability for use."""
    use_native = native_split_enabled()
    if use_native:
        # the cost-aware planner may DECLINE native for histories
        # below its fitted threshold (it can never force native on
        # past the user's gate pin); both splitters produce identical
        # per-key lists, so the tier choice moves only time
        from . import planner as _planner
        pl = _planner.get()
        if pl is not None and not pl.split_native(len(history)):
            use_native = False
    if use_native:
        from . import native_lib
        got = native_lib.split_key_ids(path)
        if got is not None:
            keys, key_ids = got
            if len(key_ids) == len(history):
                if stats is not None:
                    stats["native"] = stats.get("native", 0) + 1
                trace.counter("split.native").inc()
                return _subhistories_from_ids(history, key_ids.tolist(),
                                              keys)
            # benign: the caller loaded an edited/different file than
            # the one on disk — a documented fallback, not a broken lib
            native_lib.count_fallback("split_key_ids")
    if stats is not None:
        stats["python"] = stats.get("python", 0) + 1
    trace.counter("split.python").inc()
    return subhistories(relift_history(history))


def subhistories(history: list) -> dict:
    """Every key's subhistory in ONE pass — identical per-key lists to
    subhistory(k, ...) but O(ops + keys·unlifted) instead of the
    per-key scan's O(keys·ops), which dominates store-wide register
    sweeps (hundreds of keys per run). Keys appear in first-seen order
    (dict ordering); un-lifted ops land in every key's list, including
    keys first seen later (their list starts with the un-lifted prefix
    so far, exactly as the per-key filter has it)."""
    subs: dict = {}
    unlifted: list = []
    for o in history:
        v = o.get("value")
        if is_tuple(v):
            lst = subs.get(v.key)
            if lst is None:
                lst = subs[v.key] = list(unlifted)
            lst.append({**o, "value": v.value})
        else:
            unlifted.append(o)
            for lst in subs.values():
                lst.append(o)
    return subs


class IndependentChecker(Checker):
    """Check each key's subhistory with the sub-checker
    (independent.clj:451-502).

    If the sub-checker exposes `check_batch(test, histories, opts)`,
    all subhistories go down in one batched device dispatch (the TPU
    path); otherwise they fan out over a bounded thread pool like the
    reference's bounded-pmap."""

    def __init__(self, sub: Checker):
        self.sub = sub

    @staticmethod
    def _sub_opts(opts: dict, k) -> dict:
        """Per-key opts: nest artifact output under independent/<key> so
        store-writing sub-checkers (timeline, perf plots...) don't clobber
        each other across keys (independent.clj:474-478)."""
        base = opts.get("subdirectory")
        base = ([base] if isinstance(base, str) else list(base or []))
        return {**opts, "subdirectory": base + ["independent", str(k)],
                "history-key": k}

    def _persist_key(self, test: dict, opts: dict, k, sub: list,
                     result: dict) -> None:
        """Write per-key results.edn + history.edn (independent.clj:480-488)."""
        store = test.get("store")
        if store is None:
            return
        from . import edn, history as h
        from .store import _results_to_edn
        sub_opts = self._sub_opts(opts, k)
        d = store.path(test, *sub_opts["subdirectory"], "results.edn")
        d.write_text(edn.dumps(_results_to_edn(result)) + "\n")
        d.parent.joinpath("history.edn").write_text(h.history_to_edn(sub))

    def check(self, test, history, opts):
        opts = opts or {}
        with trace.span("independent.split", ops=len(history)):
            by_key = subhistories(history)
        ks = list(by_key)
        subs = [by_key[k] for k in ks]
        if hasattr(self.sub, "check_batch"):
            # Batch checkers get the shared opts (one device dispatch, no
            # per-key namespacing) and so must not write store artifacts
            # themselves; per-key results/history are persisted below.
            try:
                with trace.span("independent.check_batch", keys=len(ks)):
                    results = self.sub.check_batch(test, subs, opts)
            except Exception:
                results = [check_safe(self.sub, test, s, self._sub_opts(opts, k))
                           for k, s in zip(ks, subs)]
        else:

            def _one(ks_):
                k, s = ks_
                with trace.span("independent.key", key=str(k)):
                    return check_safe(self.sub, test, s,
                                      self._sub_opts(opts, k))

            results = bounded_pmap(_one, list(zip(ks, subs)))
        # Batch-dispatched sub-checkers never see per-key opts, so any
        # per-failure artifact (e.g. linear.svg) is rendered here, where
        # the per-key subdirectory is known.
        render = getattr(self.sub, "render_failure", None)
        if render is not None:
            for k, s, r in zip(ks, subs, results):
                if r.get("valid?") is False:
                    try:
                        render(test, s, r, self._sub_opts(opts, k))
                    except Exception:
                        log.warning("failure render for key %r failed",
                                    k, exc_info=True)
        for k, s, r in zip(ks, subs, results):
            try:
                self._persist_key(test, opts, k, s, r)
            except Exception:
                log.warning("couldn't persist results for key %r",
                            k, exc_info=True)
        result_map = dict(zip(ks, results))
        failures = [k for k, r in result_map.items()
                    if r.get("valid?") is False]
        return {
            "valid?": merge_valid(
                [r.get("valid?", True) for r in results] or [True]),
            "results": result_map,
            "failures": failures,
        }


def checker(sub: Checker) -> Checker:
    return IndependentChecker(sub)
