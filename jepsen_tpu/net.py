"""Network manipulation: partitions, latency, packet loss.

Counterpart of jepsen.net (jepsen/src/jepsen/net.clj): a `Net` protocol
(drop/heal/slow/flaky/fast, net.clj:15-26) with an iptables
implementation including the all-at-once grudge fast path
(net.clj:101-114) and tc/netem for slow/flaky links (net.clj:71-89).
"""

from __future__ import annotations

from typing import Iterable

from . import control
from .control import Lit
from .control import net as cnet


class Net:
    def drop(self, test: dict, src: str, dst: str) -> None:
        """Drop traffic from src to dst (delivered to dst's firewall)."""
        raise NotImplementedError

    def drop_all(self, test: dict, grudge: dict) -> None:
        """Apply a grudge: {node: set-of-nodes-to-drop-traffic-from}.
        Default: one drop per edge; implementations may batch."""
        for node, snubbed in grudge.items():
            for src in snubbed:
                self.drop(test, src, node)

    def heal(self, test: dict) -> None:
        raise NotImplementedError

    def slow(self, test: dict, mean_ms: float = 50, variance_ms: float = 10,
             distribution: str = "normal") -> None:
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        raise NotImplementedError


class IptablesNet(Net):
    """iptables + tc netem (net.clj:58-114)."""

    def _sess(self, test, node) -> control.Session:
        return control.session(test, node).su()

    def drop(self, test, src, dst):
        sess = self._sess(test, dst)
        sess.exec("iptables", "-A", "INPUT", "-s",
                  cnet.ip(sess, src), "-j", "DROP", "-w")

    def drop_all(self, test, grudge):
        """Fast path: one iptables invocation per node with a joined
        source list (PartitionAll, net/proto.clj:6-13, net.clj:101-114)."""
        def apply1(t, node):
            snubbed = grudge.get(node) or ()
            if not snubbed:
                return
            sess = control.current_session().su()
            ips = ",".join(sorted(cnet.ip(sess, s) for s in snubbed))
            sess.exec("iptables", "-A", "INPUT", "-s", ips, "-j", "DROP",
                      "-w")

        control.on_nodes(test, apply1,
                         [n for n in grudge if grudge.get(n)])

    def heal(self, test):
        def heal1(t, node):
            sess = control.current_session().su()
            sess.exec("iptables", "-F", "-w")
            sess.exec("iptables", "-X", "-w")

        control.on_nodes(test, heal1)

    def slow(self, test, mean_ms=50, variance_ms=10, distribution="normal"):
        def slow1(t, node):
            control.current_session().su().exec(
                "tc", "qdisc", "add", "dev", "eth0", "root", "netem",
                "delay", f"{mean_ms}ms", f"{variance_ms}ms",
                "distribution", distribution)

        control.on_nodes(test, slow1)

    def flaky(self, test):
        def flaky1(t, node):
            control.current_session().su().exec(
                "tc", "qdisc", "add", "dev", "eth0", "root", "netem",
                "loss", "20%", "75%")

        control.on_nodes(test, flaky1)

    def fast(self, test):
        def fast1(t, node):
            control.current_session().su().exec_ok(
                "tc", "qdisc", "del", "dev", "eth0", "root")

        control.on_nodes(test, fast1)


class IPFilterNet(IptablesNet):
    """ipfilter rules for SmartOS nodes (net.clj:116-148): same tc netem
    slow/flaky/fast as iptables, different drop/heal commands."""

    @staticmethod
    def _block_rules(sess, srcs) -> str:
        return "\\n".join(f"block in from {cnet.ip(sess, s)} to any"
                          for s in srcs)

    def drop(self, test, src, dst):
        sess = self._sess(test, dst)
        sess.exec(Lit(
            f"printf '%b\\n' \"{self._block_rules(sess, [src])}\""
            f" | ipf -f -"))

    def drop_all(self, test, grudge):
        # The whole grudge lands in ONE ipf invocation per node so the
        # partition applies atomically, like the iptables fast path.
        def apply1(t, node):
            sess = control.current_session().su()
            rules = self._block_rules(sess, sorted(grudge.get(node) or ()))
            sess.exec(Lit(f"printf '%b\\n' \"{rules}\" | ipf -f -"))

        control.on_nodes(test, apply1,
                         [n for n in grudge if grudge.get(n)])

    def heal(self, test):
        def heal1(t, node):
            control.current_session().su().exec("ipf", "-Fa")

        control.on_nodes(test, heal1)


class NoopNet(Net):
    """For tests and dummy runs: records grudges on itself."""

    def __init__(self):
        self.grudges: list[dict] = []
        self.healed = 0

    def drop(self, test, src, dst):
        self.grudges.append({dst: {src}})

    def drop_all(self, test, grudge):
        self.grudges.append(grudge)

    def heal(self, test):
        self.healed += 1

    def slow(self, test, **kw):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass


def ipfilter() -> Net:
    return IPFilterNet()


def iptables() -> Net:
    return IptablesNet()


def noop() -> Net:
    return NoopNet()


def net_for(test: dict) -> Net:
    n = test.get("net")
    if n is None:
        n = NoopNet() if test.get("ssh", {}).get("dummy") else IptablesNet()
        test["net"] = n
    return n
