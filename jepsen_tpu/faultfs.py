"""Disk-fault injection through the faultfs FUSE filesystem.

Counterpart of the reference's CharybdeFS suite
(charybdefs/src/jepsen/charybdefs.clj): a fault-injecting filesystem is
built from source on each DB node and mounted at /faulty over a /real
backing dir (install!, charybdefs.clj:41-65); the nemesis then flips
fault modes mid-test (break-all / break-one-percent / clear,
charybdefs.clj:72-85). Our filesystem is native/faultfs.cc — an original
C++ FUSE passthrough controlled by writing commands to
``<mount>/.faultfs-ctl`` over plain SSH, replacing the reference's
Thrift control server.
"""

from __future__ import annotations

import logging
import os.path

from . import control
from .nemesis import Nemesis

log = logging.getLogger(__name__)

FAULTFS_DIR = "/opt/jepsen"
FAULTFS_BIN = f"{FAULTFS_DIR}/faultfs"
NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")

REAL_DIR = "/real"
MOUNT_DIR = "/faulty"
CTL = f"{MOUNT_DIR}/.faultfs-ctl"


def install(test: dict | None = None, node: str | None = None) -> None:
    """Build faultfs on the node and mount it (install!,
    charybdefs.clj:41-65). Requires fuse + libfuse-dev, installed via
    the node's package manager."""
    sess = control.current_session()
    su = sess.su()
    su.exec_ok("apt-get", "install", "-y", "fuse", "libfuse-dev",
               "pkg-config", "g++")
    su.exec("mkdir", "-p", FAULTFS_DIR)
    src = os.path.join(NATIVE_DIR, "faultfs.cc")
    sess.upload(src, "/tmp/faultfs.cc")
    su.exec("mv", "/tmp/faultfs.cc", f"{FAULTFS_DIR}/faultfs.cc")
    su.exec(control.Lit(
        f"g++ -O2 -o {FAULTFS_BIN} {FAULTFS_DIR}/faultfs.cc "
        f"$(pkg-config fuse --cflags --libs)"))
    mount(test, node)


def mount(test: dict | None = None, node: str | None = None) -> None:
    """(Re)mount /faulty over /real (charybdefs.clj:64-70)."""
    su = control.current_session().su()
    su.exec_ok("modprobe", "fuse")
    su.exec_ok("umount", MOUNT_DIR)
    su.exec("mkdir", "-p", REAL_DIR, MOUNT_DIR)
    su.exec(FAULTFS_BIN, REAL_DIR, MOUNT_DIR, "-o",
            "allow_other,default_permissions")
    su.exec("chmod", "777", REAL_DIR, MOUNT_DIR)


def unmount(test: dict | None = None, node: str | None = None) -> None:
    control.current_session().su().exec_ok("umount", MOUNT_DIR)


def _ctl(cmd: str) -> None:
    sess = control.current_session()
    shell = f"echo {control.escape(cmd)} > {CTL}"
    res = sess.exec_raw(shell)
    if res.exit != 0:
        raise control.CommandError(shell, res.exit, res.out, res.err,
                                   sess.node)


def break_all(test: dict | None = None, node: str | None = None) -> None:
    """All operations fail with EIO (break-all, charybdefs.clj:72-75)."""
    _ctl("eio 1")


def break_probability(p: float = 0.01, test: dict | None = None,
                      node: str | None = None) -> None:
    """A fraction p of operations fail with EIO (break-one-percent,
    charybdefs.clj:77-80)."""
    _ctl(f"eio {float(p)}")


def break_errno(code: int, p: float = 1.0) -> None:
    """A fraction p of operations fail with the given errno."""
    _ctl(f"errno {int(code)} {float(p)}")


def delay(micros: int, p: float = 1.0) -> None:
    """A fraction p of operations sleep for `micros` first."""
    _ctl(f"delay {int(micros)} {float(p)}")


def clear(test: dict | None = None, node: str | None = None) -> None:
    """Remove all injected faults (clear, charybdefs.clj:82-85)."""
    _ctl("clear")


# ---------------------------------------------------------------------------
# Local (no-FUSE) write-fault injection.
#
# The FUSE layer above needs root + a DB node; the store's own
# durability protocols (the flushed append-journal, the atomic
# snapshot) want crash-sim coverage in plain tier-1 tests. This is
# the deterministic counterpart: a byte-budgeted `open()` replacement
# whose write-mode files stop mid-`write()` once the budget runs out
# — the partial bytes are flushed to disk first, which is exactly
# the torn tail a SIGKILL (or a full disk / EIO) leaves behind.
# tests/test_costdb.py drives `append_costdb`/`merge_costdbs` through
# it and asserts seal + skip + idempotent re-merge.
# ---------------------------------------------------------------------------

class FaultyWriteFile:
    """Wraps a real text-mode file: writes draw down a shared
    character budget; the write that exhausts it lands its prefix on
    disk (flushed — the crash point must be observable) and raises
    EIO. Reads and bookkeeping pass through."""

    def __init__(self, f, budget: dict):
        self._f = f
        self._budget = budget

    def write(self, data):
        left = self._budget["left"]
        if left <= 0:
            raise OSError(5, "faultfs: injected write fault")
        if len(data) <= left:
            self._budget["left"] = left - len(data)
            return self._f.write(data)
        self._f.write(data[:left])
        self._f.flush()
        self._budget["left"] = 0
        raise OSError(5, "faultfs: injected short write "
                         f"({left} of {len(data)} bytes landed)")

    def writelines(self, lines):
        # route through write() so the budget applies — delegating
        # via __getattr__ would silently bypass the injection
        for ln in lines:
            self.write(ln)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def __getattr__(self, name):
        return getattr(self._f, name)


def faulty_opener(budget_chars: int, real_open=open):
    """An `open()` replacement that injects a crash after
    `budget_chars` characters of write-mode output (shared across
    every file it opens — the budget models the process's remaining
    lifetime, not one file's). Read-mode opens pass through
    untouched. Use with monkeypatch:

        monkeypatch.setattr("builtins.open",
                            faultfs.faulty_opener(120))
    """
    budget = {"left": int(budget_chars)}

    def _open(file, mode="r", *args, **kwargs):
        f = real_open(file, mode, *args, **kwargs)
        if any(c in mode for c in "wax+") and "b" not in mode:
            return FaultyWriteFile(f, budget)
        return f

    return _open


class FaultFSNemesis(Nemesis):
    """Nemesis driving faultfs on target nodes. Ops:

        {:f "break-all",  :value [nodes] | None}
        {:f "break-pct",  :value p | [nodes, p]}
        {:f "delay",      :value micros | [nodes, micros]}
        {:f "clear",      :value [nodes] | None}

    None targets every node. Mirrors the charybdefs suite's
    client/nemesis (charybdefs.clj:93-128)."""

    def setup(self, test):
        control.on_nodes(test, lambda t, n: install(t, n))
        return self

    def invoke(self, test, op):
        f = op.get("f")
        v = op.get("value")
        nodes, arg = test.get("nodes", []), None
        if isinstance(v, (list, tuple)) and v and isinstance(v[0], (list, tuple)):
            nodes, arg = v[0], (v[1] if len(v) > 1 else None)
        elif isinstance(v, (list, tuple)) and v and isinstance(v[0], str):
            nodes = v
        elif v is not None:
            arg = v

        def act(t, n):
            if f == "break-all":
                break_all(t, n)
            elif f == "break-pct":
                break_probability(arg if arg is not None else 0.01, t, n)
            elif f == "delay":
                delay(int(arg if arg is not None else 100_000))
            elif f == "clear":
                clear(t, n)
            else:
                raise ValueError(f"unknown faultfs op {f!r}")

        control.on_nodes(test, act, nodes=list(nodes))
        return {**op, "type": "info"}

    def teardown(self, test):
        try:
            control.on_nodes(test, lambda t, n: clear(t, n))
        except Exception:
            log.warning("faultfs teardown clear failed", exc_info=True)


def nemesis() -> Nemesis:
    return FaultFSNemesis()
