"""JT-DUR — store-artifact durability: every on-disk format a sweep
persists must speak its declared crash-consistency protocol.

Jepsen's history is only ground truth if it survives the faults the
harness injects (PAPER.md): since PR 4 the repo has accumulated ~10
durability-critical store formats, each hand-implementing one of two
protocols — the flushed append-journal with torn-tail-tolerant
readers, or the atomic temp+`os.replace` snapshot — and nothing but
convention stopped the next subsystem (the serve daemon, store
compaction) from writing a torn file that silently loses a verdict.
These rules prove the protocols statically against the
`contracts.STORE_ARTIFACTS` registry, over the file-effect analysis
in `fileflow.py`:

  * JT-DUR-001 — a store-rooted path not declared in the registry;
  * JT-DUR-002 — a snapshot/marker artifact published without
    temp+`os.replace`;
  * JT-DUR-003 — an append handle whose last write is never flushed,
    or a record split across multiple `write()` calls;
  * JT-DUR-004 — a journal/spool read that bypasses the shared
    torn-tail seal/skip reader;
  * JT-DUR-005 — an append-forever artifact with no declared
    retention class;
  * JT-DUR-006 — the generated README "Store durability" table
    drifted from the registry (`make dur-table`).

The mutation harness (tests/test_durability_prover.py) seeds each
violation into a copy of the real modules and asserts exactly its
rule fires — the prover is itself proved, the JT-ABI precedent.
"""

from __future__ import annotations

from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, ProjectCtx, ProjectRule
from . import contracts, fileflow

_JOURNALISH = ("journal", "spool")
_ATOMIC = ("snapshot", "marker")


def _sanctioned(rel: str, qualname: str, specs: tuple[str, ...]) -> bool:
    return f"{rel}:{qualname}" in specs


class UndeclaredStoreArtifact(ModuleRule):
    id = "JT-DUR-001"
    doc = ("a store-rooted (or cache-rooted) file path whose name is "
           "not declared in the STORE_ARTIFACTS registry — an on-disk "
           "format with no certified crash-consistency protocol")
    hint = ("declare the artifact (pattern, protocol, writers, "
            "readers, retention) in lint/contracts.py STORE_ARTIFACTS "
            "and regenerate the README table (make dur-table)")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for sc in fileflow.analyze(ctx).scopes:
            for node, tail, _root in sc.joins:
                # directories (no dot) are namespace, not artifacts;
                # `.tmp` names are the atomic publishes' scratch
                if "." not in tail or tail.endswith(".tmp"):
                    continue
                if contracts.artifact_for_name(tail) is None:
                    yield self.finding(
                        ctx, node,
                        f"store-rooted artifact {tail!r} is not "
                        "declared in STORE_ARTIFACTS")


class NonAtomicSnapshotPublish(ModuleRule):
    id = "JT-DUR-002"
    doc = ("a snapshot/marker-class artifact written directly on its "
           "final name (`open(path, 'w')` / `.write_text`) instead of "
           "temp+`os.replace` — a crash mid-write leaves a torn file "
           "where a reader expects a complete one")
    hint = ("publish via trace.atomic_write_text (or write a .tmp "
            "sibling and os.replace it over the final name)")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for sc in fileflow.analyze(ctx).scopes:
            for node, tail, mode in sc.opens:
                if tail is None or not any(c in mode for c in "wxa+"):
                    continue
                art = contracts.artifact_for_name(tail)
                if art is not None and art.protocol in _ATOMIC:
                    yield self.finding(
                        ctx, node,
                        f"{art.protocol} artifact {tail!r} opened "
                        f"for writing ({mode!r}) on its final name")
            for node, tail in sc.write_texts:
                art = contracts.artifact_for_name(tail)
                if art is not None and art.protocol in _ATOMIC:
                    yield self.finding(
                        ctx, node,
                        f"{art.protocol} artifact {tail!r} published "
                        "via a direct write on its final name")


class UnflushedJournalAppend(ModuleRule):
    id = "JT-DUR-003"
    doc = ("an append-mode handle whose last write() is never "
           "flush()ed (an explicit close() counts — it drains the "
           "buffer and ends observability; the implicit with-exit "
           "does not) before the handle can be observed (returned, "
           "stored, or the process dies), or a record assembled "
           "across multiple write() calls — either way a crash "
           "tears or loses the record (the journal protocol is one "
           "write per line, flushed as it lands)")
    hint = ("build the full line (json.dumps(rec) + '\\n'), write it "
            "with ONE write(), and flush() immediately after")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for sc in fileflow.analyze(ctx).scopes:
            for key, evs in sc.handles.items():
                # one lexical sweep: `last_w` is the most recent write
                # with no flush after it — a bare-"\n" write while one
                # is pending is a record split across two writes, and
                # a pending write at scope end is the unflushed tail
                last_w = None
                for _line, kind, node, is_nl in evs:
                    if kind in ("flush", "close"):
                        # an explicit close() drains the buffer and
                        # ends observability — the with-exit close is
                        # deliberately NOT tracked (a loop of buffered
                        # writes inside a with-block still loses them
                        # all on a mid-loop crash)
                        last_w = None
                    elif kind == "write":
                        if is_nl and last_w is not None:
                            yield self.finding(
                                ctx, node,
                                f"record on append handle {key!r} is "
                                "split across multiple write() calls "
                                "— a crash between them tears the "
                                "line mid-record")
                        last_w = node
                if last_w is not None:
                    yield self.finding(
                        ctx, last_w,
                        f"append handle {key!r}: no flush() after "
                        "its last write() — the record is lost (or "
                        "torn) if the process dies with it buffered")


class RawJournalReader(ModuleRule):
    id = "JT-DUR-004"
    doc = ("a journal/spool-class artifact read with raw json.loads "
           "over raw lines instead of the shared torn-tail seal/skip "
           "reader — a crash-torn tail poisons the load instead of "
           "being skipped")
    hint = ("read through the artifact's declared reader "
            "(VerdictJournal.load / load_costdb / load_events / "
            "load_spool) — they skip the torn tail")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for sc in fileflow.analyze(ctx).scopes:
            if not sc.has_json_loads:
                continue
            reads = list(sc.read_texts)
            for node, tail, mode in sc.opens:
                if tail is not None \
                        and not any(c in mode for c in "wxa+"):
                    reads.append((node, tail))
            for node, tail in reads:
                art = contracts.artifact_for_name(tail)
                if art is None or art.protocol not in _JOURNALISH:
                    continue
                if _sanctioned(ctx.rel, sc.qualname, art.readers):
                    continue
                yield self.finding(
                    ctx, node,
                    f"raw read of {art.protocol} artifact {tail!r} "
                    "bypasses its torn-tail-tolerant reader")


class UndeclaredRetention(ProjectRule):
    id = "JT-DUR-005"
    doc = ("an append-forever (journal/spool) artifact in the "
           "STORE_ARTIFACTS registry with no declared retention "
           "class — unbounded growth with nobody owning the bound "
           "(the static half of ROADMAP item 5's retention lever)")
    hint = ("declare one of contracts.RETENTION_CLASSES on the "
            "registry entry (and make it true: rotation, merge, or "
            "per-sweep cleanup)")

    _REL = "jepsen_tpu/lint/contracts.py"

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        for a in contracts.STORE_ARTIFACTS:
            if a.protocol in _JOURNALISH \
                    and a.retention not in contracts.RETENTION_CLASSES:
                yield Finding(
                    self.id, self._REL, 1,
                    f"{a.protocol} artifact {a.name!r} declares no "
                    f"valid retention class (got {a.retention!r})",
                    self.hint)
            elif a.retention is not None \
                    and a.retention not in contracts.RETENTION_CLASSES:
                yield Finding(
                    self.id, self._REL, 1,
                    f"artifact {a.name!r} declares unknown retention "
                    f"class {a.retention!r}", self.hint)


class DurTableDrift(ProjectRule):
    id = "JT-DUR-006"
    doc = ("the committed README \"Store durability\" table must "
           "match the STORE_ARTIFACTS registry render exactly")
    hint = "regenerate: make dur-table"

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        readme = ctx.root / "README.md"
        if not readme.is_file():
            return   # installed-package context: nothing to check
        text = readme.read_text(encoding="utf-8")
        if contracts.DUR_BEGIN not in text \
                or contracts.DUR_END not in text:
            yield Finding(self.id, "README.md", 1,
                          "store-durability table markers missing "
                          f"({contracts.DUR_BEGIN!r})", self.hint)
            return
        start = text.index(contracts.DUR_BEGIN)
        end = text.index(contracts.DUR_END) + len(contracts.DUR_END)
        line = text[:start].count("\n") + 1
        if text[start:end].strip() != contracts.render_dur_block().strip():
            yield Finding(self.id, "README.md", line,
                          "store-durability table drifted from the "
                          "STORE_ARTIFACTS registry", self.hint)


RULES = [UndeclaredStoreArtifact(), NonAtomicSnapshotPublish(),
         UnflushedJournalAppend(), RawJournalReader(),
         UndeclaredRetention(), DurTableDrift()]
