"""JT-WIRE — frame-protocol drift checking for the JTSV wire format.

`serve/protocol.py` declares the frame-kind registry (`FRAME_OPS`):
every `op` either side may put on the wire, its direction, and its
required/optional payload keys. Three parties speak it — the tenant
client, the verdict daemon, and the fleet router that forwards both
directions — and nothing but convention stopped a new frame kind (or
a renamed handler string) from becoming a silently-dropped frame.
These rules prove sender/handler agreement statically, the JT-ABI
discipline applied python↔python:

  * JT-WIRE-001 — an emitted op not declared in FRAME_OPS, a
    declared op its receiving side never handles (c2d → daemon.py,
    d2c → client.py), or a handled op string the registry does not
    declare. The fleet router is EXCLUDED from handler obligations:
    its pump forwards unmatched frames verbatim (that catch-all is
    the router's contract), but its own emissions are still checked.
  * JT-WIRE-002 — an emitted frame literal missing one of its op's
    required keys (retry-after without `queue_depth` is backpressure
    the client cannot obey).
  * JT-WIRE-003 — a duplicated wire constant (the magic bytes or the
    length cap re-spelled outside protocol.py — the constant the
    next refactor forgets to update), or the generated README frame
    table drifting from the registry (`make wire-table`).

Everything is decided on the PARSED registry — the protocol module's
AST via the shared `ProjectCtx.module()` parse, never an import — so
fixture copies of the serve modules check exactly like the live tree
(tests/test_wire_prover.py seeds one drift per rule and pins exactly
the expected finding).

Visibility rules, stated once: a frame is tracked when it is a dict
literal at the send site or a local name built from dict literals
(assign, ``.update({...})``, ``name["k"] = v``); a frame whose base
is opaque (``dict(conn.hello or {})``) contributes its op to the
agreement check but is exempt from required-key proof; a frame whose
op is not a literal is invisible on purpose (the router's forwarded
frames). Emission sites are calls to ``*.send(frame)``,
``send_frame(sock, frame)`` and ``*._submit(frame)``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, ProjectCtx, ProjectRule, dotted
from . import dataflow

__all__ = ["RULES", "WIRE_BEGIN", "WIRE_END",
           "render_wire_table", "render_wire_block"]

_PROTOCOL = "jepsen_tpu/serve/protocol.py"
#: (module rel, handler side it implements: "c2d" means it HANDLES
#: client→daemon ops). The fleet router implements neither side's
#: handler obligations — its pump forwards what it does not consume.
_SPEAKERS = (
    ("jepsen_tpu/serve/client.py", "d2c"),
    ("jepsen_tpu/serve/daemon.py", "c2d"),
    ("jepsen_tpu/serve/fleet.py", None),
)


# ---------------------------------------------------------------------------
# Registry + module scans (shared per ProjectCtx)
# ---------------------------------------------------------------------------

def _const_int(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift):
        left = _const_int(node.left)
        right = _const_int(node.right)
        if left is not None and right is not None:
            return left << right
    return None


def _str_tuple(node: ast.AST) -> tuple[str, ...]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


class _Registry:
    """FRAME_OPS parsed from the protocol module's AST, plus the wire
    constants (magic bytes, frame cap) JT-WIRE-003 guards."""

    def __init__(self, tree: ast.Module):
        self.ops: dict[str, dict] = {}
        self.magic: bytes | None = None
        self.max_frame: int | None = None
        for n in tree.body:
            tgt = None
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                tgt, val = n.targets[0], n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                tgt, val = n.target, n.value
            else:
                continue
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == "MAGIC" and isinstance(val, ast.Constant) \
                    and isinstance(val.value, bytes):
                self.magic = val.value
            elif tgt.id == "MAX_FRAME":
                self.max_frame = _const_int(val)
            elif tgt.id == "FRAME_OPS" and isinstance(val, ast.Dict):
                for k, v in zip(val.keys, val.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            and isinstance(v, ast.Dict)):
                        continue
                    spec: dict = {"line": k.lineno, "dir": "",
                                  "required": (), "optional": (),
                                  "doc": ""}
                    for fk, fv in zip(v.keys, v.values):
                        if not (isinstance(fk, ast.Constant)
                                and isinstance(fk.value, str)):
                            continue
                        if fk.value in ("dir", "doc") \
                                and isinstance(fv, ast.Constant):
                            spec[fk.value] = fv.value
                        elif fk.value in ("required", "optional"):
                            spec[fk.value] = _str_tuple(fv)
                    self.ops[k.value] = spec


_AMBIG = object()


def _dict_info(d: ast.Dict):
    """(op, keys, open) of a dict literal: `open` when it spreads or
    carries a non-constant key, `op` _AMBIG when the "op" value is
    not a string literal."""
    op = None
    keys: set[str] = set()
    open_ = False
    for k, v in zip(d.keys, d.values):
        if k is None or not (isinstance(k, ast.Constant)
                             and isinstance(k.value, str)):
            open_ = True      # **spread / computed key
            continue
        keys.add(k.value)
        if k.value == "op":
            op = v.value if (isinstance(v, ast.Constant)
                             and isinstance(v.value, str)) else _AMBIG
    return op, keys, open_


def _is_op_fetch(node: ast.AST) -> bool:
    """`X.get("op")` or `X["op"]`."""
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args:
        a = node.args[0]
        return isinstance(a, ast.Constant) and a.value == "op"
    if isinstance(node, ast.Subscript):
        s = node.slice
        return isinstance(s, ast.Constant) and s.value == "op"
    return False


class _ModuleScan:
    """One speaker module: its frame emissions (op, keys or None when
    the base is opaque, line), the op strings its dispatch handles,
    and any re-spelled wire constants."""

    def __init__(self, tree: ast.Module, magic: bytes | None,
                 max_frame: int | None):
        self.emissions: list[tuple[str, frozenset | None, int]] = []
        self.handled: dict[str, int] = {}
        self.alien_consts: list[tuple[str, int]] = []

        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            self._scan_scope(scope)
        for n in ast.walk(tree):
            # handler dispatch: names bound from X.get("op")/X["op"]
            # are collected per module below; constants first
            if isinstance(n, ast.Constant):
                if magic is not None and n.value == magic \
                        and isinstance(n.value, bytes):
                    self.alien_consts.append(("magic bytes", n.lineno))
            elif isinstance(n, ast.BinOp):
                v = _const_int(n)
                if max_frame is not None and v == max_frame:
                    self.alien_consts.append(("frame cap", n.lineno))
        if max_frame is not None:
            for n in ast.walk(tree):
                if isinstance(n, ast.Constant) and not isinstance(
                        n.value, bool) and n.value == max_frame:
                    self.alien_consts.append(("frame cap", n.lineno))
        self._scan_handlers(tree)

    def _scan_scope(self, scope: ast.AST) -> None:
        nodes = list(dataflow.own_nodes(scope))
        frames: dict[str, dict] = {}
        for n in nodes:
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                t = n.targets[0]
                if isinstance(t, ast.Name):
                    ent = frames.setdefault(
                        t.id, {"op": None, "keys": set(),
                               "open": False})
                    if isinstance(n.value, ast.Dict):
                        op, keys, open_ = _dict_info(n.value)
                        if op is not None:
                            ent["op"] = op if ent["op"] in (None, op) \
                                else _AMBIG
                        ent["keys"] |= keys
                        ent["open"] |= open_
                    else:
                        ent["open"] = True
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and isinstance(t.slice, ast.Constant) \
                        and isinstance(t.slice.value, str):
                    ent = frames.setdefault(
                        t.value.id, {"op": None, "keys": set(),
                                     "open": False})
                    ent["keys"].add(t.slice.value)
                    if t.slice.value == "op":
                        v = n.value
                        op = v.value if (isinstance(v, ast.Constant)
                                         and isinstance(v.value, str)) \
                            else _AMBIG
                        ent["op"] = op if ent["op"] in (None, op) \
                            else _AMBIG
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "update" \
                    and isinstance(n.func.value, ast.Name) \
                    and n.args and isinstance(n.args[0], ast.Dict):
                ent = frames.setdefault(
                    n.func.value.id, {"op": None, "keys": set(),
                                      "open": False})
                op, keys, open_ = _dict_info(n.args[0])
                if op is not None:
                    ent["op"] = op if ent["op"] in (None, op) \
                        else _AMBIG
                ent["keys"] |= keys
                ent["open"] |= open_
        for n in nodes:
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            if d is None:
                continue
            if d == "send_frame" or d.endswith(".send_frame"):
                arg = n.args[1] if len(n.args) > 1 else None
            elif d.endswith(".send") or d.endswith("._submit"):
                arg = n.args[0] if n.args else None
            else:
                continue
            if isinstance(arg, ast.Dict):
                op, keys, open_ = _dict_info(arg)
            elif isinstance(arg, ast.Name) and arg.id in frames:
                ent = frames[arg.id]
                op, keys, open_ = ent["op"], ent["keys"], ent["open"]
            else:
                continue   # opaque frame (forwarded/param) — invisible
            if not isinstance(op, str):
                continue   # no literal op — invisible on purpose
            self.emissions.append(
                (op, None if open_ else frozenset(keys), n.lineno))

    def _scan_handlers(self, tree: ast.Module) -> None:
        op_names = {n.targets[0].id for n in ast.walk(tree)
                    if isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and _is_op_fetch(n.value)}
        for n in ast.walk(tree):
            if not isinstance(n, ast.Compare):
                continue
            left_is_op = (isinstance(n.left, ast.Name)
                          and n.left.id in op_names) \
                or _is_op_fetch(n.left)
            if not left_is_op:
                continue
            if not all(isinstance(o, (ast.Eq, ast.NotEq, ast.In,
                                      ast.NotIn)) for o in n.ops):
                continue
            for comp in n.comparators:
                if isinstance(comp, ast.Constant) \
                        and isinstance(comp.value, str):
                    self.handled.setdefault(comp.value, n.lineno)
                elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    for e in comp.elts:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, str):
                            self.handled.setdefault(e.value, n.lineno)


class _WireState:
    """The whole-protocol view, built once per ProjectCtx run from
    the shared parses and consumed by all three rules."""

    def __init__(self, ctx: ProjectCtx):
        self.protocol_rel = _PROTOCOL
        proto = ctx.module(_PROTOCOL)
        self.present = proto is not None
        self.registry = _Registry(proto.tree) if proto else None
        self.scans: dict[str, _ModuleScan] = {}
        self.sides: dict[str, str | None] = {}
        if self.registry is None:
            return
        for rel, side in _SPEAKERS:
            m = ctx.module(rel)
            if m is None:
                continue    # degraded tree (fixtures) — skip
            self.scans[rel] = _ModuleScan(m.tree, self.registry.magic,
                                          self.registry.max_frame)
            self.sides[rel] = side


def _state(ctx: ProjectCtx) -> _WireState:
    st = getattr(ctx, "_wire_state", None)
    if st is None:
        st = _WireState(ctx)
        ctx._wire_state = st
    return st


# ---------------------------------------------------------------------------
# README frame table
# ---------------------------------------------------------------------------

WIRE_BEGIN = ("<!-- wire-frames:begin "
              "(generated by jepsen_tpu.lint.wireflow) -->")
WIRE_END = "<!-- wire-frames:end -->"

_DIRS = {"c2d": "client → daemon", "d2c": "daemon → client"}


def render_wire_table(registry: _Registry) -> str:
    rows = ["| op | direction | required | optional | notes |",
            "|---|---|---|---|---|"]
    for op, spec in registry.ops.items():
        req = ", ".join(f"`{k}`" for k in spec["required"]) or "—"
        opt = ", ".join(f"`{k}`" for k in spec["optional"]) or "—"
        rows.append(f"| `{op}` | {_DIRS.get(spec['dir'], spec['dir'])}"
                    f" | {req} | {opt} | {spec['doc']} |")
    return "\n".join(rows)


def render_wire_block(registry: _Registry) -> str:
    return f"{WIRE_BEGIN}\n{render_wire_table(registry)}\n{WIRE_END}"


def live_registry(root) -> "_Registry | None":
    """The registry parsed from `root`'s protocol module — the
    `make wire-table` entry point (one renderer, fed the same way
    the drift check feeds itself)."""
    p = root / _PROTOCOL
    if not p.is_file():
        return None
    return _Registry(ast.parse(p.read_text(encoding="utf-8"),
                               filename=str(p)))


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

class FrameAgreement(ProjectRule):
    id = "JT-WIRE-001"
    doc = ("sender/handler agreement with the FRAME_OPS registry: an "
           "emitted op the registry does not declare, a declared op "
           "its receiving side (daemon for c2d, client for d2c) "
           "never handles — a silently-dropped frame — or a handled "
           "op string the registry does not declare")
    hint = ("declare the op (direction, required/optional keys) in "
            "serve/protocol.py FRAME_OPS and handle it on the "
            "receiving side; run `make wire-table` after")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        st = _state(ctx)
        if not st.present:
            return
        reg = st.registry
        if not reg.ops:
            yield Finding(self.id, st.protocol_rel, 1,
                          "FRAME_OPS registry missing or empty — the "
                          "wire protocol has no source of truth to "
                          "prove senders/handlers against", self.hint)
            return
        handled_by_side: dict[str, dict[str, int]] = {}
        for rel, scan in st.scans.items():
            side = st.sides.get(rel)
            if side is not None:
                handled_by_side[side] = scan.handled
            for op, _keys, line in scan.emissions:
                if op not in reg.ops:
                    yield Finding(
                        self.id, rel, line,
                        f"emits op {op!r} not declared in FRAME_OPS",
                        self.hint)
            for op, line in scan.handled.items():
                if op not in reg.ops:
                    yield Finding(
                        self.id, rel, line,
                        f"handles op {op!r} not declared in "
                        f"FRAME_OPS — dead dispatch or registry "
                        f"drift", self.hint)
        for op, spec in reg.ops.items():
            side = spec["dir"]
            if side not in handled_by_side:
                continue   # degraded tree without the handler module
            if op not in handled_by_side[side]:
                who = "daemon.py" if side == "c2d" else "client.py"
                yield Finding(
                    self.id, st.protocol_rel, spec["line"],
                    f"declared op {op!r} ({_DIRS.get(side, side)}) "
                    f"is never handled by {who} — a frame the "
                    f"receiver silently drops", self.hint)


class RequiredFrameFields(ProjectRule):
    id = "JT-WIRE-002"
    doc = ("an emitted frame literal missing one of its op's "
           "required keys — backpressure without queue_depth, a "
           "verdict without its result — caught at the send site")
    hint = ("carry every FRAME_OPS required key on the frame literal "
            "(or update the registry if the contract changed)")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        st = _state(ctx)
        if not st.present or not st.registry.ops:
            return
        for rel, scan in st.scans.items():
            for op, keys, line in scan.emissions:
                spec = st.registry.ops.get(op)
                if spec is None or keys is None:
                    continue   # WIRE-001's problem / opaque base
                missing = [k for k in spec["required"]
                           if k not in keys]
                if missing:
                    yield Finding(
                        self.id, rel, line,
                        f"{op!r} frame missing required "
                        f"key(s) {missing} (FRAME_OPS requires "
                        f"{list(spec['required'])})", self.hint)


class WireConstantDrift(ProjectRule):
    id = "JT-WIRE-003"
    doc = ("a wire constant re-spelled outside protocol.py (the "
           "magic bytes or the frame cap duplicated where the next "
           "protocol change forgets it), or the generated README "
           "frame table drifting from the registry")
    hint = ("import MAGIC/MAX_FRAME from serve/protocol.py instead "
            "of re-spelling them; regenerate the README table with "
            "`make wire-table`")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        st = _state(ctx)
        if not st.present or st.registry is None:
            return
        for rel, scan in st.scans.items():
            for what, line in scan.alien_consts:
                yield Finding(
                    self.id, rel, line,
                    f"wire {what} re-spelled outside protocol.py — "
                    f"a duplicated constant the next protocol bump "
                    f"will miss", self.hint)
        readme = ctx.root / "README.md"
        if not readme.is_file() or not st.registry.ops:
            return   # installed-package / fixture context
        text = readme.read_text(encoding="utf-8")
        if WIRE_BEGIN not in text or WIRE_END not in text:
            yield Finding(
                self.id, "README.md", 1,
                "missing the generated wire-frame table markers — "
                "add them and run `make wire-table`", self.hint)
            return
        start = text.index(WIRE_BEGIN)
        end = text.index(WIRE_END) + len(WIRE_END)
        if text[start:end] != render_wire_block(st.registry):
            line = text[:start].count("\n") + 1
            yield Finding(
                self.id, "README.md", line,
                "wire-frame table drifted from serve/protocol.py "
                "FRAME_OPS — run `make wire-table`", self.hint)


RULES = [FrameAgreement(), RequiredFrameFields(), WireConstantDrift()]
