"""Per-function control-flow graphs with a lockset analysis (JT-LOCK's
engine).

`build_cfg(fn, lock_resolver)` lowers one function body to basic
blocks of pseudo-instructions — plain statements plus explicit
``enter``/``exit`` markers for every ``with``-acquired lock — and
`compute_locksets` runs a forward MUST-analysis over the graph
(IN = ∩ OUT over predecessors, so a lock only counts as held when it
is held on EVERY path). The result maps each statement to the set of
lock ids held when it executes; rules then ask "was the registry's
lock held at this write?" or "which locks were held at this call
site?" without re-deriving control flow.

Lock identity is the caller's business: `lock_resolver(expr)` returns
a stable id ("_MLOCK", "DeviceSlotLedger._lock") for a with-item that
is a lock, or None for ordinary context managers — the analysis never
guesses what is a lock. `with` is also the only acquisition form the
package sanctions (JT-THREAD-002 bans bare `.acquire()`), which is
what lets exceptional exits stay sound: Python releases with-held
locks on ANY exit, and every in-body statement the rules inspect is
lexically inside the with, where the must-set is exact.

The module also builds the module-local call graph (`call_graph`) the
lock-order analysis walks: qualified names resolved for bare local
functions and `self.method` calls — enough to see `f` holding lock A
call `g` that takes lock B two files of indirection away would need
whole-program resolution, but every inversion this repo has actually
shipped lived inside one module.

Beyond locksets, the graphs carry what a path-sensitive ordering
prover (JT-ORD) needs: `CFG.branches` records each lowered `if`'s
branch polarity (cond block → (then-start, else-start)) so a search
can prune one arm of a known guard, `return`/`raise`/`break`/
`continue` are routed THROUGH every enclosing `finally` body (lowered
as copies on the abnormal edge — `compute_locksets` intersects over
duplicate statement occurrences, so the must-sets stay sound), and
`dominators`/`post_dominators` solve the classic block-level dataflow
for "A on every path to B" / "B on every path from A" questions.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Iterator

__all__ = [
    "Block", "CFG", "build_cfg", "compute_locksets",
    "dominators", "post_dominators",
    "iter_defs", "call_graph", "resolve_call",
]

LockResolver = Callable[[ast.AST], "str | None"]


@dataclass
class Block:
    id: int
    #: ("stmt", node) | ("enter", lock_id, node) | ("exit", lock_id, node)
    instrs: list = field(default_factory=list)
    succs: set = field(default_factory=set)


class CFG:
    def __init__(self) -> None:
        self.blocks: dict[int, Block] = {}
        #: cond-block id → (then-start id, else-start id) for every
        #: lowered `if` (each `if` ends its block, so the key is
        #: unambiguous) — the branch polarity guard-aware searches need
        self.branches: dict[int, tuple[int, int]] = {}
        self.entry = self._new().id
        self.exit = self._new().id

    def _new(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks[b.id] = b
        return b

    def edge(self, a: int, b: int) -> None:
        self.blocks[a].succs.add(b)


class _Builder:
    def __init__(self, resolver: LockResolver):
        self.cfg = CFG()
        self.resolver = resolver
        self.cur = self.cfg._new()
        self.cfg.edge(self.cfg.entry, self.cur.id)
        self.loops: list[tuple[int, int]] = []   # (head, after)
        #: pending finally bodies: (finalbody, len(self.loops) at push)
        self.finallies: list[tuple[list, int]] = []

    def _start(self, *preds: int) -> Block:
        b = self.cfg._new()
        for p in preds:
            self.cfg.edge(p, b.id)
        return b

    def _terminated(self) -> bool:
        return self.cur is None

    def _unwind(self, stop: int) -> None:
        """An abnormal exit (`return`/`raise`/`break`/`continue`) runs
        every enclosing `finally` body down to stack index `stop`
        before leaving — lower COPIES of them (innermost first) into
        the current chain. Each copy is lowered with the stack
        truncated below itself, so a `return` INSIDE a finally body
        unwinds only the finallies outer to it."""
        saved = self.finallies
        try:
            for i in range(len(saved) - 1, stop - 1, -1):
                self.finallies = saved[:i]
                self.stmts(saved[i][0])
                if self._terminated():
                    # the finally body itself returned/raised/broke:
                    # it replaced this exit and already unwound the rest
                    return
        finally:
            self.finallies = saved

    def _loop_finallies(self) -> int:
        """The unwind stop for `break`/`continue`: only finallies
        pushed INSIDE the current loop (push depth >= current loop
        depth) run before the jump; outer ones stay pending."""
        stop = len(self.finallies)
        while stop and self.finallies[stop - 1][1] >= len(self.loops):
            stop -= 1
        return stop

    def stmts(self, body: list[ast.stmt]) -> None:
        for s in body:
            if self._terminated():
                # unreachable code still gets a block so lockset_of
                # answers for every statement
                self.cur = self.cfg._new()
            self.stmt(s)

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.If):
            self.cur.instrs.append(("stmt", s))
            cond = self.cur
            self.cur = self._start(cond.id)
            then_start = self.cur.id
            self.stmts(s.body)
            then_end = self.cur
            self.cur = self._start(cond.id)
            self.cfg.branches[cond.id] = (then_start, self.cur.id)
            self.stmts(s.orelse)
            else_end = self.cur
            join = self.cfg._new()
            for e in (then_end, else_end):
                if e is not None:
                    self.cfg.edge(e.id, join.id)
            self.cur = join
        elif isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            self.cur.instrs.append(("stmt", s))
            head = self._start(self.cur.id)
            after = self.cfg._new()
            self.cfg.edge(head.id, after.id)   # zero-trip / cond false
            self.loops.append((head.id, after.id))
            self.cur = self._start(head.id)
            self.stmts(s.body)
            if self.cur is not None:
                self.cfg.edge(self.cur.id, head.id)   # back edge
            self.loops.pop()
            if s.orelse:
                self.cur = self._start(after.id)
                self.stmts(s.orelse)
                if self.cur is not None:
                    after = self._start(self.cur.id)
                else:
                    after = self.cfg.blocks[self.cfg._new().id]
            self.cur = after
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            self.cur.instrs.append(("stmt", s))
            locks = []
            for item in s.items:
                lid = self.resolver(item.context_expr)
                if lid is not None:
                    locks.append(lid)
                    self.cur.instrs.append(("enter", lid, s))
            self.stmts(s.body)
            if self.cur is not None:
                for lid in reversed(locks):
                    self.cur.instrs.append(("exit", lid, s))
        elif isinstance(s, ast.Try):
            self.cur.instrs.append(("stmt", s))
            if s.finalbody:
                self.finallies.append((s.finalbody, len(self.loops)))
            entry = self.cur
            self.cur = self._start(entry.id)
            self.stmts(s.body)
            body_end = self.cur
            ends = [body_end] if body_end is not None else []
            for h in s.handlers:
                # conservatively reachable from the try entry (an
                # exception can fire before any body statement runs)
                self.cur = self._start(entry.id)
                if body_end is not None:
                    self.cfg.edge(body_end.id, self.cur.id)
                self.stmts(h.body)
                if self.cur is not None:
                    ends.append(self.cur)
            if s.orelse and body_end is not None:
                self.cur = self._start(body_end.id)
                self.stmts(s.orelse)
                ends = [e for e in ends if e is not body_end]
                if self.cur is not None:
                    ends.append(self.cur)
            join = self.cfg._new()
            for e in ends:
                self.cfg.edge(e.id, join.id)
            self.cur = join
            if s.finalbody:
                self.finallies.pop()
                self.stmts(s.finalbody)
        elif isinstance(s, (ast.Return, ast.Raise)):
            self.cur.instrs.append(("stmt", s))
            self._unwind(0)
            if self.cur is not None:
                self.cfg.edge(self.cur.id, self.cfg.exit)
            self.cur = None
        elif isinstance(s, ast.Break):
            self.cur.instrs.append(("stmt", s))
            self._unwind(self._loop_finallies())
            if self.cur is not None and self.loops:
                self.cfg.edge(self.cur.id, self.loops[-1][1])
            self.cur = None
        elif isinstance(s, ast.Continue):
            self.cur.instrs.append(("stmt", s))
            self._unwind(self._loop_finallies())
            if self.cur is not None and self.loops:
                self.cfg.edge(self.cur.id, self.loops[-1][0])
            self.cur = None
        else:
            # leaf statements — including nested def/class, whose
            # bodies are separate CFGs, not this one's statements
            self.cur.instrs.append(("stmt", s))


def build_cfg(fn: ast.AST, lock_resolver: LockResolver) -> CFG:
    """The CFG of one function body (or a Module treated as a body)."""
    b = _Builder(lock_resolver)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    b.stmts([s for s in body
             if not isinstance(s, (ast.FunctionDef,
                                   ast.AsyncFunctionDef,
                                   ast.ClassDef))])
    if b.cur is not None:
        b.cfg.edge(b.cur.id, b.cfg.exit)
    return b.cfg


def compute_locksets(cfg: CFG) -> dict[int, frozenset[str]]:
    """id(statement node) → MUST-held lock set. Fixpoint of the
    forward analysis; unreachable blocks start from the empty set."""
    ALL = object()
    out: dict[int, object] = {i: ALL for i in cfg.blocks}
    out[cfg.entry] = frozenset()
    preds: dict[int, list[int]] = {i: [] for i in cfg.blocks}
    for b in cfg.blocks.values():
        for s in b.succs:
            preds[s].append(b.id)
    changed = True
    while changed:
        changed = False
        for bid, b in cfg.blocks.items():
            ins = [out[p] for p in preds[bid] if out[p] is not ALL]
            cur: frozenset[str] = \
                frozenset.intersection(*ins) if ins else frozenset()
            for ins_kind in b.instrs:
                if ins_kind[0] == "enter":
                    cur = cur | {ins_kind[1]}
                elif ins_kind[0] == "exit":
                    cur = cur - {ins_kind[1]}
            if out[bid] is ALL or out[bid] != cur:
                out[bid] = cur
                changed = True

    result: dict[int, frozenset[str]] = {}
    for bid, b in cfg.blocks.items():
        ins2 = [out[p] for p in preds[bid] if out[p] is not ALL]
        cur = frozenset.intersection(*ins2) if ins2 else frozenset()
        for kind in b.instrs:
            if kind[0] == "enter":
                cur = cur | {kind[1]}
            elif kind[0] == "exit":
                cur = cur - {kind[1]}
            else:
                node = kind[1]
                # the lockset when the statement executes: a with
                # statement's own node reports the set INSIDE it
                held = result.get(id(node))
                result[id(node)] = cur if held is None else (cur & held)
    # a with-statement node itself should report its body's set: the
    # enter instr is ("enter", lock_id, with_node)
    for bid, b in cfg.blocks.items():
        for kind in b.instrs:
            if kind[0] == "enter":
                node = kind[2]
                result[id(node)] = result.get(id(node),
                                              frozenset()) | {kind[1]}
    return result


def _dom_solve(ids: set, start: int,
               preds: dict) -> dict[int, frozenset[int]]:
    dom = {i: frozenset(ids) for i in ids}
    dom[start] = frozenset({start})
    changed = True
    while changed:
        changed = False
        for i in ids:
            if i == start:
                continue
            ins = [dom[p] for p in preds[i]]
            new = (frozenset.intersection(*ins)
                   if ins else frozenset(ids)) | {i}
            if new != dom[i]:
                dom[i] = new
                changed = True
    return dom


def dominators(cfg: CFG) -> dict[int, frozenset[int]]:
    """block id → blocks on EVERY entry→block path (reflexive).
    Blocks unreachable from entry report the full set — vacuously
    dominated, which is what path queries want."""
    preds: dict[int, list[int]] = {i: [] for i in cfg.blocks}
    for b in cfg.blocks.values():
        for s in b.succs:
            preds[s].append(b.id)
    return _dom_solve(set(cfg.blocks), cfg.entry, preds)


def post_dominators(cfg: CFG) -> dict[int, frozenset[int]]:
    """block id → blocks on EVERY block→exit path (reflexive): the
    dominance solve on the reversed graph, anchored at cfg.exit."""
    # reversed graph: the predecessors of i are i's forward successors
    preds = {i: list(b.succs) for i, b in cfg.blocks.items()}
    return _dom_solve(set(cfg.blocks), cfg.exit, preds)


# ---------------------------------------------------------------------------
# Module-local call graph
# ---------------------------------------------------------------------------

def iter_defs(tree: ast.Module) -> Iterator[tuple[str, str | None,
                                                  ast.AST]]:
    """(qualname, class name or None, node) for every function in the
    module, including methods and nested defs (qualname `outer.inner`)."""
    def walk(node: ast.AST, prefix: str, cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, cls, child
                yield from walk(child, q + ".", cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, child.name + ".", child.name)

    yield from walk(tree, "", None)


def resolve_call(call: ast.Call, *, cls: str | None,
                 local_fns: set[str],
                 methods: dict[str, set[str]],
                 enclosing: str = "") -> str | None:
    """The qualname a call resolves to within this module, or None:
    bare local function names, `ClassName(...)` → its `__init__`, and
    `self.method()` / `ClassName.method()` within the module. A call
    on any OTHER receiver stays unresolved on purpose — guessing an
    owner from a bare method name (`.close()`, `.get()`) would wire
    unrelated objects into the lock graph."""
    f = call.func
    if isinstance(f, ast.Name):
        if enclosing:
            nested = f"{enclosing}.{f.id}"
            if nested in local_fns:
                return nested
        if f.id in local_fns:
            return f.id
        if f.id in methods and "__init__" in methods[f.id]:
            return f"{f.id}.__init__"
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id == "self" and cls is not None \
                and f.attr in methods.get(cls, ()):
            return f"{cls}.{f.attr}"
        if f.value.id in methods and f.attr in methods[f.value.id]:
            return f"{f.value.id}.{f.attr}"
    return None


def call_graph(tree: ast.Module) -> dict[str, set[str]]:
    """qualname → set of locally-resolved callee qualnames."""
    defs = list(iter_defs(tree))
    local_fns = {q for q, _c, _n in defs}
    methods: dict[str, set[str]] = {}
    for q, c, _n in defs:
        if c is not None and q.startswith(c + "."):
            methods.setdefault(c, set()).add(q.split(".", 1)[1])
    out: dict[str, set[str]] = {}
    for q, c, node in defs:
        callees: set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                r = resolve_call(n, cls=c, local_fns=local_fns,
                                 methods=methods, enclosing=q)
                if r is not None and r != q:
                    callees.add(r)
        out[q] = callees
    return out
