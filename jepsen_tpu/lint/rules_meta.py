"""JT-META — the linter's own documentation surface.

The README rule table is GENERATED from the rule registry
(`lint.render_rule_block`, `make rule-table`) the same way the
env-gate table is generated from `gates.py`; this rule fails the run
when the committed table drifts, and tests/test_lint.py additionally
pins the full rule-id list so a rule can't be renumbered or silently
dropped without a diff a reviewer sees.
"""

from __future__ import annotations

from typing import Iterator

from . import Finding, ProjectCtx, ProjectRule


class RuleTableDrift(ProjectRule):
    id = "JT-META-001"
    doc = ("the committed README rule table must match the rule "
           "registry render exactly")
    hint = "regenerate: make rule-table"

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        from . import RULES_BEGIN, RULES_END, render_rule_block
        readme = ctx.root / "README.md"
        if not readme.is_file():
            return   # installed-package context: nothing to check
        text = readme.read_text(encoding="utf-8")
        if RULES_BEGIN not in text or RULES_END not in text:
            yield Finding(self.id, "README.md", 1,
                          f"rule-table markers missing "
                          f"({RULES_BEGIN!r})", self.hint)
            return
        start = text.index(RULES_BEGIN)
        end = text.index(RULES_END) + len(RULES_END)
        committed = text[start:end].strip()
        line = text[:start].count("\n") + 1
        if committed != render_rule_block().strip():
            yield Finding(self.id, "README.md", line,
                          "rule table drifted from the rule registry",
                          self.hint)


RULES = [RuleTableDrift()]
