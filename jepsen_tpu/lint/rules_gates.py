"""JT-GATE — the env-gate registry family.

Every `JEPSEN_TPU_*` env var must be declared once in
`jepsen_tpu.gates` and read only through its typed accessors; the
README env-gate table is rendered from the registry and must not
drift; every registered gate must appear in test coverage. This is
the rule family that turns 21 ad-hoc `os.environ` reads into one
audited surface.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from . import (Finding, ModuleCtx, ModuleRule, ProjectCtx, ProjectRule,
               const_str, dotted)

#: The one file where raw environ access to gate names is sanctioned.
_GATES_FILE = "jepsen_tpu/gates.py"

_ACCESSORS = {"get", "get_raw", "export", "unset", "is_set", "gate"}


def _registered() -> set[str]:
    from .. import gates
    return set(gates.GATES)


def _is_environ(node: ast.AST) -> bool:
    d = dotted(node)
    return d is not None and (d == "environ" or d.endswith(".environ"))


def _environs(ctx) -> list:
    """The module's environ accesses, memoized on the ModuleCtx —
    both gate rules share one walk per file."""
    cached = getattr(ctx, "_gate_environs", None)
    if cached is None:
        cached = list(_environ_accesses(ctx.tree))
        ctx._gate_environs = cached
    return cached


def _environ_accesses(tree: ast.AST) -> Iterator[tuple[ast.AST, str | None]]:
    """(node, gate-name literal) for every environ read/write/del whose
    key is a string constant (dynamic keys can't be resolved
    statically and are out of scope)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d and (d == "getenv" or d.endswith(".getenv")):
                yield node, const_str(node.args[0]) if node.args else None
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in ("get", "pop", "setdefault")
                  and _is_environ(node.func.value)):
                yield node, const_str(node.args[0]) if node.args else None
        elif isinstance(node, ast.Subscript) and _is_environ(node.value):
            yield node, const_str(node.slice)
        elif isinstance(node, ast.Compare) and len(node.comparators) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and _is_environ(node.comparators[0]):
            yield node, const_str(node.left)


class RawGateAccess(ModuleRule):
    id = "JT-GATE-001"
    doc = ("raw os.environ/os.getenv access of a JEPSEN_TPU_* name "
           "outside the gates registry")
    hint = ("read/write the gate through jepsen_tpu.gates "
            "(gates.get/export/unset)")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel.endswith(_GATES_FILE):
            return
        for node, name in _environs(ctx):
            if name and name.startswith("JEPSEN_TPU_"):
                yield self.finding(
                    ctx, node,
                    f"raw environ access of gate {name!r}")


class UnregisteredGate(ModuleRule):
    id = "JT-GATE-002"
    doc = ("a JEPSEN_TPU_* name used in an env/gates access that is "
           "not declared in the gates registry (typo, or an "
           "undeclared gate)")
    hint = "declare it in jepsen_tpu/gates.py (name, kind, default, doc)"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        reg = _registered()
        seen: set[tuple[int, str]] = set()

        def emit(node, name):
            key = (getattr(node, "lineno", 1), name)
            if key not in seen:
                seen.add(key)
                yield self.finding(ctx, node,
                                   f"unregistered gate {name!r}")

        for node, name in _environs(ctx):
            if name and name.startswith("JEPSEN_TPU_") \
                    and name not in reg:
                yield from emit(node, name)
        # gates-accessor calls with an unregistered literal: these
        # raise KeyError at runtime — catch them before they ship.
        # Track how THIS module names the gates module (import aliases
        # like `gates as _gates`) and which accessors it imported bare
        # (`from ..gates import get`), so aliased reads aren't a blind
        # spot.
        aliases = {"gates"}
        bare: set[str] = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.ImportFrom):
                mod = n.module or ""
                if mod == "gates" or mod.endswith(".gates"):
                    bare.update(a.asname or a.name for a in n.names
                                if a.name in _ACCESSORS)
                else:
                    aliases.update(a.asname or a.name for a in n.names
                                   if a.name == "gates")
            elif isinstance(n, ast.Import):
                for a in n.names:
                    if a.name == "gates" or a.name.endswith(".gates"):
                        aliases.add(a.asname or a.name.split(".")[0])
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = const_str(node.args[0]) if node.args else None
            if not (name and name.startswith("JEPSEN_TPU_")
                    and name not in reg):
                continue
            d = dotted(node.func)
            if d and "." in d:
                head, _, tail = d.rpartition(".")
                if tail in _ACCESSORS \
                        and aliases.intersection(head.split(".")):
                    yield from emit(node, name)
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in bare:
                yield from emit(node, name)


class ReadmeTableDrift(ProjectRule):
    id = "JT-GATE-003"
    doc = ("the committed README env-gate table must match the "
           "registry render exactly")
    hint = ("regenerate: python -c \"from jepsen_tpu import gates; "
            "print(gates.render_env_block())\" and paste between the "
            "markers in README.md")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        from .. import gates
        readme = ctx.root / "README.md"
        if not readme.is_file():
            return   # installed-package context: nothing to check
        text = readme.read_text(encoding="utf-8")
        if gates.TABLE_BEGIN not in text or gates.TABLE_END not in text:
            yield Finding(self.id, "README.md", 1,
                          "env-gate table markers missing "
                          f"({gates.TABLE_BEGIN!r})", self.hint)
            return
        start = text.index(gates.TABLE_BEGIN)
        end = text.index(gates.TABLE_END) + len(gates.TABLE_END)
        committed = text[start:end].strip()
        line = text[:start].count("\n") + 1
        if committed != gates.render_env_block().strip():
            yield Finding(self.id, "README.md", line,
                          "env-gate table drifted from the "
                          "gates registry", self.hint)


class GateTestCoverage(ProjectRule):
    id = "JT-GATE-004"
    doc = ("every registered gate must appear by name somewhere under "
           "tests/ — a gate without a test is unverified behavior")
    hint = ("add a test exercising the gate (and its row to "
            "tests/test_gates.py's literal drift list)")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        from .. import gates
        tests = ctx.root / "tests"
        if not tests.is_dir():
            return
        # lint fixture files mention gate names to seed violations —
        # they are not coverage
        blob = "\n".join(
            p.read_text(encoding="utf-8", errors="replace")
            for p in sorted(tests.rglob("*.py"))
            if "lint_fixtures" not in p.parts)
        for name in gates.GATES:
            # word-boundary match: JEPSEN_TPU_TRACE must not count as
            # covered just because JEPSEN_TPU_TRACE_MAX_EVENTS is
            if not re.search(re.escape(name) + r"(?![A-Z0-9_])", blob):
                yield Finding(self.id, "tests", 1,
                              f"gate {name} has no test coverage",
                              self.hint)


RULES = [RawGateAccess(), UnregisteredGate(), ReadmeTableDrift(),
         GateTestCoverage()]
