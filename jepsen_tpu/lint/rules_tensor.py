"""JT-TENSOR — tensor-contract dataflow over the encode→pack→dispatch
path.

The CPU/TPU verdict-parity guarantee rides on a handful of tensor
contracts nothing used to check statically: the encoded arrays'
dtypes (int32 triples/status/process, int64 lean indexes, int32
`d_invoke`/`d_complete` device tensors), pack_batch's fill convention
(-1 dead triples/process, 0 dead index rows), the bucket pad geometry
(txn axis 128, minor axes 8 — `dispatch_pad_plan` == BatchShape.plan
== hist_encode.cc's pad_up), and the donated-arg positions of a
single-device dispatch. Each lives in `lint/contracts.py` ONCE; these
rules run the `dataflow` tag analysis over the files that build or
consume the tensors and flag any operation that disagrees with the
registry.

  JT-TENSOR-001  undeclared dtype cast of a contracted tensor
  JT-TENSOR-002  host materialization on the pack/h2d hot path
                 (subsumes and strengthens the retired JT-JAX-005)
  JT-TENSOR-003  fill-convention / pad-geometry / triple-shape drift
  JT-TENSOR-004  donate_argnums drift from the declared positions
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, dotted
from . import contracts, dataflow

_NP_NAMES = {"np", "numpy", "jnp", "onp"}
#: Host-side numpy spellings only — `jnp.pad` is the ON-DEVICE pad the
#: warm path uses on purpose; flagging it would invert the contract.
_HOST_NP_NAMES = {"np", "numpy", "onp"}

#: Array constructors with (shape, fill?, dtype?) worth checking.
_CTORS_FILL = {"full": (1, 2), "zeros": (None, 1), "ones": (None, 1),
               "empty": (None, 1)}
_CTOR_IMPLICIT_FILL = {"zeros": 0, "ones": 1}

_COPY_FNS = {"copy", "ascontiguousarray", "pad", "array"}
_PAD_FN_NAMES = {"pad_to", "_pad_up", "pad_up"}


def _np_call(n: ast.AST) -> str | None:
    """'full' for np.full(...) / jnp.full(...), else None."""
    if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
            and isinstance(n.func.value, ast.Name) \
            and n.func.value.id in _NP_NAMES:
        return n.func.attr
    return None


def _kw(call: ast.Call, name: str) -> ast.AST | None:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _dtype_arg(call: ast.Call, pos: int | None) -> ast.AST | None:
    v = _kw(call, "dtype")
    if v is not None:
        return v
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


def _dataflow_scopes(ctx: ModuleCtx):
    """Where the dataflow rules look: every scope of a declared
    tensor file, or — anywhere else — just the hot-path-named
    functions (pack_*/_h2d/...), which is also what makes the rules
    fixture-testable outside the package tree."""
    if contracts.is_tensor_file(ctx.rel):
        yield from dataflow.iter_scopes(ctx.tree)
        return
    for fn in ast.walk(ctx.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and any(fn.name.startswith(p)
                        for p in contracts.HOT_FN_PREFIXES):
            yield fn


def _scoped(ctx: ModuleCtx) -> list:
    """(scope, tags, own-nodes) per dataflow scope, memoized on the
    ModuleCtx — the tensor rules share one tag build and one
    stop-at-nested-defs walk per scope instead of redoing both per
    rule."""
    cached = getattr(ctx, "_tensor_scopes", None)
    if cached is None:
        cached = [(sc, dataflow.build_tags(sc),
                   list(dataflow.own_nodes(sc)))
                  for sc in _dataflow_scopes(ctx)]
        ctx._tensor_scopes = cached
    return cached


def _target_field(t: ast.AST) -> str | None:
    """The contracted field an assignment target names: `appends = …`,
    `d_invoke[:n] = …`, `out["reads"] = …`."""
    if isinstance(t, ast.Name):
        return contracts.field_of(t.id)
    if isinstance(t, ast.Subscript):
        from . import const_str
        ks = const_str(t.slice)
        if ks is not None:
            return contracts.field_of(ks)
        if isinstance(t.value, ast.Name):
            return contracts.field_of(t.value.id)
    return None


class UndeclaredCast(ModuleRule):
    id = "JT-TENSOR-001"
    doc = ("a dtype cast of a contracted encoded tensor that the "
           "contracts registry does not declare — the device kernels "
           "consume these dtypes verbatim, so a stray cast silently "
           "forks the TPU verdict from the CPU checkers")
    hint = ("keep the declared dtype (lint/contracts.TENSOR_DTYPES), "
            "or register the narrowing in DECLARED_NARROWINGS if both "
            "writers perform it")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for scope, tags, nodes in _scoped(ctx):
            for n in nodes:
                if not isinstance(n, ast.Call):
                    continue
                src = dt = None
                f = n.func
                if isinstance(f, ast.Attribute) and f.attr == "astype" \
                        and n.args:
                    src = dataflow.tag_of(f.value, tags)
                    dt = dataflow.resolve_dtype(n.args[0])
                else:
                    name = _np_call(n)
                    if name in ("asarray", "array",
                                "ascontiguousarray") and n.args:
                        src = dataflow.tag_of(n.args[0], tags)
                        dt = dataflow.resolve_dtype(
                            _dtype_arg(n, 1))
                if src is None or dt is None:
                    continue
                want = contracts.TENSOR_DTYPES[src]
                if dt != want and (src, dt) not in \
                        contracts.DECLARED_NARROWINGS:
                    yield self.finding(
                        ctx, n,
                        f"undeclared cast of `{src}` "
                        f"({want} by contract) to {dt}")


class HostMaterialization(ModuleRule):
    id = "JT-TENSOR-002"
    doc = ("np.copy/ascontiguousarray/pad/array or .tolist() on the "
           "pack/h2d hot path — a host-side materialization between "
           "the store mmap and device_put, exactly what the "
           "dispatch-shaped sidecars exist to remove (subsumes "
           "JT-JAX-005)")
    hint = ("feed device_put the mmap/shm view directly (v2 sidecar "
            "dispatch views), or justify the copy inline with "
            "`# jt-lint: ok JT-TENSOR-002 (reason)`")

    def _hot_scopes(self, ctx: ModuleCtx) -> Iterator[ast.AST]:
        """Per-FUNCTION scopes (so build_tags sees each scope's local
        bindings — a whole-module scope would leave the tag map empty
        exactly in the hot files this rule targets): every scope of a
        hot-path file, or the hot-named functions (plus their nested
        defs) anywhere else."""
        if contracts.is_hot_path_file(ctx.rel):
            yield from dataflow.iter_scopes(ctx.tree)
            return
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(fn.name.startswith(p)
                            for p in contracts.HOT_FN_PREFIXES):
                for n in ast.walk(fn):
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        yield n

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        seen: set[int] = set()
        for scope in self._hot_scopes(ctx):
            tags = dataflow.build_tags(scope)
            for n in dataflow.own_nodes(scope):
                if not isinstance(n, ast.Call) or id(n) in seen:
                    continue
                name = _np_call(n)
                if name in _COPY_FNS \
                        and n.func.value.id in _HOST_NP_NAMES:
                    if name == "array" and not (
                            n.args and dataflow.tag_of(n.args[0],
                                                       tags)):
                        # np.array on small host metadata is fine —
                        # only a contracted tensor is a copy that
                        # matters at bucket scale
                        continue
                    seen.add(id(n))
                    yield self.finding(
                        ctx, n,
                        f"np.{name}() host copy on the pack/h2d "
                        "hot path")
                elif isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "tolist" \
                        and dataflow.tag_of(n.func.value, tags):
                    seen.add(id(n))
                    yield self.finding(
                        ctx, n,
                        "contracted tensor .tolist() on the pack/h2d "
                        "hot path — a full host materialization")


class FillAndGeometryDrift(ModuleRule):
    id = "JT-TENSOR-003"
    doc = ("a contracted tensor built with the wrong fill or dtype, a "
           "pad call with an undeclared multiple, or a triple field "
           "reshaped off its [N,3] layout — the kernels' dead-row "
           "masking and the MXU tile geometry both assume the "
           "registry's values")
    hint = ("fill convention: -1 for triples/process, 0 for index "
            "rows; pad multiples: 128 (txns) / 8 (minor) — see "
            "lint/contracts.py")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        consts = dataflow.module_int_consts(ctx.tree)
        for scope, tags, nodes in _scoped(ctx):
            for n in nodes:
                # pad_to(x, M) / _pad_up(x, M) with an undeclared M
                if isinstance(n, ast.Call):
                    d = dotted(n.func)
                    tail = d.split(".")[-1] if d else ""
                    if tail in _PAD_FN_NAMES and len(n.args) >= 2:
                        m = dataflow.int_value(n.args[1], consts)
                        if m is not None and \
                                m not in contracts.PAD_MULTIPLES:
                            yield self.finding(
                                ctx, n,
                                f"pad multiple {m} is not a declared "
                                f"bucket geometry "
                                f"({sorted(contracts.PAD_MULTIPLES)})")
                    # x.reshape(..., k) off the triple layout
                    if isinstance(n.func, ast.Attribute) \
                            and n.func.attr == "reshape":
                        src = dataflow.tag_of(n.func.value, tags)
                        if src in contracts.TRIPLE_FIELDS:
                            elts = n.args[0].elts \
                                if len(n.args) == 1 and isinstance(
                                    n.args[0], ast.Tuple) else n.args
                            last = dataflow.int_value(elts[-1],
                                                      consts) \
                                if elts else None
                            if last is not None and last != 3:
                                yield self.finding(
                                    ctx, n,
                                    f"`{src}` reshaped with minor "
                                    f"axis {last} (triple fields are "
                                    "[N,3])")
                # field = np.full/zeros/ones(...): dtype + fill
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.value, ast.Call):
                    field = _target_field(n.targets[0])
                    ctor = _np_call(n.value)
                    if field is None or ctor not in _CTORS_FILL:
                        continue
                    fill_pos, dt_pos = _CTORS_FILL[ctor]
                    dt = dataflow.resolve_dtype(
                        _dtype_arg(n.value, dt_pos))
                    want_dt = contracts.TENSOR_DTYPES[field]
                    if dt is not None and dt != want_dt and \
                            (field, dt) not in \
                            contracts.DECLARED_NARROWINGS:
                        yield self.finding(
                            ctx, n.value,
                            f"`{field}` built as {dt} "
                            f"(contract: {want_dt})")
                    fill = _CTOR_IMPLICIT_FILL.get(ctor)
                    if fill_pos is not None:
                        fv = _kw(n.value, "fill_value")
                        if fv is None and \
                                len(n.value.args) > fill_pos:
                            fv = n.value.args[fill_pos]
                        fill = dataflow.int_value(fv, consts) \
                            if fv is not None else None
                    want_fill = contracts.FILL_VALUES.get(field)
                    if fill is not None and want_fill is not None \
                            and fill != want_fill:
                        yield self.finding(
                            ctx, n.value,
                            f"`{field}` filled with {fill} (pack "
                            f"convention: {want_fill})")


class DonateArgnumsDrift(ModuleRule):
    id = "JT-TENSOR-004"
    doc = ("donate_argnums differs from the declared donated-arg "
           "positions (the six packed input tensors) — donating the "
           "wrong buffer hands XLA memory the host still reads")
    hint = (f"donate exactly positions "
            f"{contracts.DONATE_ARGNUMS} (tuple(range(6)))")

    def _positions(self, v: ast.AST) -> tuple[int, ...] | None:
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                i = dataflow.int_value(e, {})
                if i is None:
                    return None
                out.append(i)
            return tuple(out)
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, ast.Call):
            d = dotted(v.func)
            if d and d.split(".")[-1] == "tuple" and v.args \
                    and isinstance(v.args[0], ast.Call):
                r = v.args[0]
                rd = dotted(r.func)
                if rd and rd.split(".")[-1] == "range" \
                        and len(r.args) == 1:
                    nmax = dataflow.int_value(r.args[0], {})
                    if nmax is not None:
                        return tuple(range(nmax))
        return None

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            v = _kw(n, "donate_argnums")
            if v is None:
                continue
            pos = self._positions(v)
            if pos is not None and pos != contracts.DONATE_ARGNUMS:
                yield self.finding(
                    ctx, n,
                    f"donate_argnums={pos} drifts from the declared "
                    f"positions {contracts.DONATE_ARGNUMS}")


RULES = [UndeclaredCast(), HostMaterialization(),
         FillAndGeometryDrift(), DonateArgnumsDrift()]
