"""JT-LOCK — lockset + thread-spawn analysis of the sweep's thread
graph.

The async sweep is a small fixed thread graph — dispatcher, pack-h2d
producer, watchdog, health sampler, /metrics handlers — sharing a
handful of structures (the donated-slot ledger, the tracer's metric
cells, the health snapshot's seq). The two bug classes the PR-6/7
review passes caught BY HAND were exactly lock-discipline drift: a
gauge published outside the lock that ordered its transitions, and a
snapshot writer that two threads could interleave. These rules run
`cfg.build_cfg` + `compute_locksets` (a MUST-hold forward analysis)
over every function and check three properties mechanically:

  JT-LOCK-001  lock-order inversion (A held while taking B and, on
               another path, B held while taking A — including
               through module-local calls) and re-entry of a
               non-reentrant Lock
  JT-LOCK-002  a write to registry-declared shared state
               (contracts.SHARED_STATE) with its guarding lock not
               held on every path
  JT-LOCK-003  a blocking call (sleep / subprocess / device wait /
               Future.result) while ANY lock is held — transitively
               through module-local calls — starving every waiter
  JT-LOCK-004  a Thread-target closure mutating state its spawner
               also mutates, with no thread-safe carrier between
               them (cross-thread mutation of thread-confined state)

Lock identity is construction-based: only names assigned from
`threading.Lock()`/`RLock()` (module globals or `self.<attr>` in
`__init__`/methods) participate, so semaphores, ledger slots and
condition variables never produce noise.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, dotted
from . import cfg as cfglib
from . import contracts, dataflow

_LOCK_CTORS = {"Lock", "RLock"}

_MUTATORS = {"append", "extend", "insert", "add", "update",
             "setdefault", "pop", "popitem", "remove", "discard",
             "clear"}


class _ModuleLocks:
    """Every lock the module constructs, with stable ids."""

    def __init__(self, tree: ast.Module):
        self.module_locks: set[str] = set()
        self.rlocks: set[str] = set()
        self.class_locks: dict[str, set[str]] = {}
        for n in tree.body:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and self._ctor(n.value):
                name = n.targets[0].id
                self.module_locks.add(name)
                if self._ctor(n.value) == "RLock":
                    self.rlocks.add(name)
        for c in ast.walk(tree):
            if not isinstance(c, ast.ClassDef):
                continue
            for n in ast.walk(c):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Attribute) \
                        and isinstance(n.targets[0].value, ast.Name) \
                        and n.targets[0].value.id == "self" \
                        and self._ctor(n.value):
                    attr = n.targets[0].attr
                    self.class_locks.setdefault(c.name, set()).add(attr)
                    if self._ctor(n.value) == "RLock":
                        self.rlocks.add(f"{c.name}.{attr}")

    @staticmethod
    def _ctor(v: ast.AST) -> str | None:
        if isinstance(v, ast.Call):
            d = dotted(v.func)
            tail = d.split(".")[-1] if d else None
            if tail in _LOCK_CTORS:
                return tail
        return None

    def resolver(self, cls: str | None):
        def resolve(expr: ast.AST) -> str | None:
            if isinstance(expr, ast.Name) \
                    and expr.id in self.module_locks:
                return expr.id
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and cls is not None \
                    and expr.attr in self.class_locks.get(cls, ()):
                return f"{cls}.{expr.attr}"
            return None
        return resolve


class _Analysis:
    """One pass shared by all JT-LOCK rules for a module: per-function
    CFGs + locksets, direct/transitive lock acquisitions, lock-order
    edges, and call sites annotated with the locks held."""

    def __init__(self, ctx: ModuleCtx):
        self.locks = _ModuleLocks(ctx.tree)
        self.defs = list(cfglib.iter_defs(ctx.tree))
        self.graph = cfglib.call_graph(ctx.tree)
        self.locksets: dict[str, dict[int, frozenset[str]]] = {}
        self.direct: dict[str, set[str]] = {}
        self.fn_of: dict[str, ast.AST] = {}
        self.cls_of: dict[str, str | None] = {}
        #: (held, acquired) -> every line the edge was observed at
        self.edges: dict[tuple[str, str], set[int]] = {}
        local_fns = {q for q, _c, _n in self.defs}
        methods: dict[str, set[str]] = {}
        for q, c, _n in self.defs:
            if c is not None and q.startswith(c + "."):
                methods.setdefault(c, set()).add(q.split(".", 1)[1])
        self.call_sites: dict[str, list] = {}
        for q, c, node in self.defs:
            self.fn_of[q] = node
            self.cls_of[q] = c
            res = self.locks.resolver(c)
            g = cfglib.build_cfg(node, res)
            ls = cfglib.compute_locksets(g)
            self.locksets[q] = ls
            acquired: set[str] = set()
            for b in g.blocks.values():
                for ins in b.instrs:
                    if ins[0] == "enter":
                        acquired.add(ins[1])
            self.direct[q] = acquired
            # nested-with acquisition edges from the exact LEXICAL
            # stack (not the CFG post-sets, which cannot distinguish a
            # re-entered lock from the genuinely-held outer instance:
            # `with _a:` inside `with _a:` must record an (a, a) edge)
            self._lexical_with_edges(node, res)
            # call sites with their NEAREST enclosing statement's
            # lockset: the own-nodes walk yields outer statements
            # before inner ones, so the most precise set wins
            site_map: dict[int, tuple[ast.Call, frozenset[str]]] = {}
            for n in cfglib_walk_own(node):
                if not isinstance(n, ast.stmt):
                    continue
                held = self._stmt_lockset(q, n)
                for call in _calls_of(n):
                    site_map[id(call)] = (call, held)
            sites = []
            for call, held in site_map.values():
                callee = cfglib.resolve_call(
                    call, cls=c, local_fns=local_fns,
                    methods=methods, enclosing=q)
                sites.append((call, callee, held))
            sites.sort(key=lambda s: s[0].lineno)
            self.call_sites[q] = sites
        # transitive acquisitions + call-graph lock edges, to fixpoint
        self.trans: dict[str, set[str]] = {
            q: set(v) for q, v in self.direct.items()}
        changed = True
        while changed:
            changed = False
            for q, callees in self.graph.items():
                for cal in callees:
                    extra = self.trans.get(cal, set()) - self.trans[q]
                    if extra:
                        self.trans[q] |= extra
                        changed = True
        for q, sites in self.call_sites.items():
            for call, callee, held in sites:
                if callee is None or not held:
                    continue
                for lid in self.trans.get(callee, ()):
                    for h in held:
                        self.edges.setdefault((h, lid),
                                              set()).add(call.lineno)

    def _lexical_with_edges(self, fn: ast.AST, res) -> None:
        """Record (held, acquired) edges from the exact lexical
        nesting of with statements, maintaining the held stack during
        the walk — this is what lets `with _a:` inside `with _a:`
        produce the (a, a) re-entry edge the CFG post-sets erase."""
        stack: list[str] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                own = 0
                for item in node.items:
                    lid = res(item.context_expr)
                    if lid is None:
                        continue
                    for h in stack:
                        self.edges.setdefault((h, lid),
                                              set()).add(node.lineno)
                    stack.append(lid)
                    own += 1
                for child in ast.iter_child_nodes(node):
                    visit(child)
                if own:
                    del stack[-own:]
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        for child in ast.iter_child_nodes(fn):
            visit(child)

    def _stmt_lockset(self, q: str, stmt: ast.AST) -> frozenset[str]:
        return self.locksets[q].get(id(stmt), frozenset())

    def stmt_locksets(self, q: str) -> Iterator[tuple[ast.stmt,
                                                      frozenset[str]]]:
        node = self.fn_of[q]
        for n in cfglib_walk_own(node):
            if isinstance(n, ast.stmt):
                yield n, self._stmt_lockset(q, n)


#: Walk a function's own body, not nested defs' (those are their own
#: analysis units) — the shared traversal from the dataflow module.
cfglib_walk_own = dataflow.own_nodes


def _calls_of(stmt: ast.AST) -> Iterator[ast.Call]:
    if isinstance(stmt, ast.stmt):
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                yield n


def _analysis(ctx: ModuleCtx) -> _Analysis:
    a = getattr(ctx, "_lock_analysis", None)
    if a is None:
        a = _Analysis(ctx)
        ctx._lock_analysis = a
    return a


class LockOrderInversion(ModuleRule):
    id = "JT-LOCK-001"
    doc = ("lock-order inversion (lock A held while acquiring B on "
           "one path, B while acquiring A on another — including "
           "through module-local calls), or a non-reentrant Lock "
           "re-acquired while held: both deadlock under the right "
           "interleaving")
    hint = ("pick one global order for the two locks (document it at "
            "the ctor) or collapse them into one; for re-entry, make "
            "the inner path lock-free and have callers hold the lock")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        a = _analysis(ctx)
        seen: set[frozenset[str]] = set()
        for (h, l2), lines in sorted(a.edges.items(),
                                     key=lambda kv: min(kv[1])):
            if h == l2:
                if l2 not in a.locks.rlocks:
                    # every re-entry site is its own deadlock
                    for line in sorted(lines):
                        yield self.finding(
                            ctx, line,
                            f"non-reentrant lock `{h}` may be "
                            "re-acquired while held (self-deadlock)")
                continue
            pair = frozenset((h, l2))
            if pair in seen:
                continue
            if (l2, h) in a.edges:
                seen.add(pair)
                other = min(a.edges[(l2, h)])
                yield self.finding(
                    ctx, min(lines),
                    f"lock-order inversion: `{h}` -> `{l2}` here, "
                    f"`{l2}` -> `{h}` at line {other}")


class UnguardedSharedWrite(ModuleRule):
    id = "JT-LOCK-002"
    doc = ("a write to registry-declared shared state "
           "(contracts.SHARED_STATE) without its guarding lock held "
           "on every path — the exact class the PR-6/7 review passes "
           "fixed by hand (ledger gauge, health snapshot seq)")
    hint = ("wrap the write in `with <declared lock>:` (__init__ is "
            "exempt — construction is single-threaded)")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        a = _analysis(ctx)
        decl: dict[str, list[tuple[str, str]]] = {}
        for cls, attr, lock in contracts.SHARED_STATE:
            decl.setdefault(cls, []).append((attr, lock))
        for q, c, _node in a.defs:
            if c is None or c not in decl:
                continue
            meth = q.split(".")[-1]
            if meth == "__init__":
                continue
            for stmt, held in a.stmt_locksets(q):
                if not isinstance(stmt, (ast.Assign, ast.AugAssign)):
                    continue
                targets = stmt.targets \
                    if isinstance(stmt, ast.Assign) else [stmt.target]
                for t in targets:
                    base = t
                    if isinstance(base, ast.Subscript):
                        base = base.value
                    if not (isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"):
                        continue
                    for attr, lock in decl[c]:
                        if base.attr != attr:
                            continue
                        want = f"{c}.{lock}" \
                            if lock in a.locks.class_locks.get(c, ()) \
                            else lock
                        if want not in held:
                            yield self.finding(
                                ctx, stmt,
                                f"`self.{attr}` ({c}) written without "
                                f"`{want}` held (declared in "
                                "contracts.SHARED_STATE)")


def _is_blocking(call: ast.Call) -> str | None:
    """The registry-declared blocking call this is, or None — driven
    entirely by contracts.BLOCKING_* so the declared surface and the
    checked surface cannot drift."""
    d = dotted(call.func)
    if d is None:
        return None
    if d in contracts.BLOCKING_EXACT:
        return d
    if d.startswith(contracts.BLOCKING_PREFIXES):
        return d
    if isinstance(call.func, ast.Attribute) \
            and d.split(".")[-1] in contracts.BLOCKING_METHOD_TAILS:
        return d
    return None


class BlockingCallUnderLock(ModuleRule):
    id = "JT-LOCK-003"
    doc = ("a blocking call (sleep, subprocess, device wait, "
           "Future.result) while a lock is held — directly or through "
           "module-local calls — every other thread touching that "
           "lock stalls for the duration")
    hint = ("move the blocking work outside the critical section "
            "(copy what you need under the lock, block after), or "
            "justify inline with `# jt-lint: ok JT-LOCK-003 (reason)`")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        a = _analysis(ctx)
        # which functions (transitively) perform a blocking call
        blocks_in: dict[str, str] = {}
        for q, _c, node in a.defs:
            for n in cfglib_walk_own(node):
                for call in _calls_of(n):
                    b = _is_blocking(call)
                    if b:
                        blocks_in.setdefault(q, b)
        trans_block: dict[str, str] = dict(blocks_in)
        changed = True
        while changed:
            changed = False
            for q, callees in a.graph.items():
                if q in trans_block:
                    continue
                for cal in callees:
                    if cal in trans_block:
                        trans_block[q] = f"{cal} -> {trans_block[cal]}"
                        changed = True
                        break
        for q, _c, node in a.defs:
            for call, callee, held in a.call_sites[q]:
                if not held:
                    continue
                b = _is_blocking(call)
                if b:
                    yield self.finding(
                        ctx, call,
                        f"blocking `{b}` while holding "
                        f"{sorted(held)}")
                elif callee in trans_block:
                    yield self.finding(
                        ctx, call,
                        f"call to `{callee}` (blocks via "
                        f"{trans_block[callee]}) while holding "
                        f"{sorted(held)}")


class CrossThreadMutation(ModuleRule):
    id = "JT-LOCK-004"
    doc = ("a Thread-target closure mutating state its spawning "
           "function also mutates, with no thread-safe carrier "
           "(Queue/Semaphore/Event/Lock) between them — "
           "thread-confined state crossed the thread boundary")
    hint = ("hand results across on a queue.Queue (the producer "
            "pattern in parallel/), or guard both sides with one "
            "lock")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for parent in ast.walk(ctx.tree):
            if not isinstance(parent, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            nested = {n.name: n for n in parent.body
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
            if not nested:
                continue
            targets = []
            for n in cfglib_walk_own(parent):
                if isinstance(n, ast.Call):
                    d = dotted(n.func)
                    if d and d.split(".")[-1] == "Thread":
                        for kw in n.keywords:
                            if kw.arg == "target" \
                                    and isinstance(kw.value, ast.Name) \
                                    and kw.value.id in nested:
                                targets.append(nested[kw.value.id])
            if not targets:
                continue
            safe = set()
            for n in cfglib_walk_own(parent):
                if isinstance(n, ast.Assign) \
                        and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and isinstance(n.value, ast.Call):
                    d = dotted(n.value.func)
                    if d and d.split(".")[-1] in \
                            contracts.THREADSAFE_CTORS:
                        safe.add(n.targets[0].id)
            parent_mut = _mutations(parent, exclude=set(nested))
            for th in targets:
                th_mut = _mutations(th, exclude=set())
                shared = (th_mut & parent_mut) - safe
                if shared:
                    yield self.finding(
                        ctx, th,
                        f"thread target `{th.name}` and its spawner "
                        f"both mutate {sorted(shared)} with no "
                        "thread-safe carrier")


def _mutations(fn: ast.AST, exclude: set[str]) -> set[str]:
    """Names a scope mutates in ways visible across threads: container
    method calls, subscript stores, and writes to `nonlocal`s. Plain
    rebinding is NOT a mutation (it creates a local)."""
    out: set[str] = set()
    nonlocals: set[str] = set()

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)) \
                    and child.name in exclude:
                continue
            yield child
            yield from walk(child)

    for n in walk(fn):
        if isinstance(n, ast.Nonlocal):
            nonlocals.update(n.names)
        elif isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATORS \
                and isinstance(n.func.value, ast.Name):
            out.add(n.func.value.id)
        elif isinstance(n, (ast.Assign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name):
                    out.add(t.value.id)
                elif isinstance(t, ast.Name) and t.id in nonlocals:
                    out.add(t.id)
    return out


RULES = [LockOrderInversion(), UnguardedSharedWrite(),
         BlockingCallUnderLock(), CrossThreadMutation()]
