"""JT-TRACE — tracer/span and metric-name discipline.

Spans must be context-managed (`with trace.span(...)`): a span object
held open across an exception never records, and manual enter/exit
splits the pairing the Chrome exporter depends on. Counter/gauge/
histogram names must come from the declared registry in
`jepsen_tpu.trace` (`DECLARED_METRICS` / `METRIC_PREFIXES`): the
metrics surface is keyed by string, so one typo silently forks a
series (`quarantined` vs `quarentined`) and every dashboard/bench
diff downstream reads half the events.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, const_str

_TRACE_FILE = "jepsen_tpu/trace.py"
_RECEIVERS = {"trace", "tr", "tracer", "jtrace"}
_METRIC_KINDS = {"counter": "counters", "gauge": "gauges",
                 "histogram": "histograms"}


def _metric_calls(tree: ast.AST) -> Iterator[tuple[ast.Call, str]]:
    """(call, kind) for tracer metric constructor calls with exactly
    one positional argument on a tracer-ish receiver."""
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _METRIC_KINDS \
                and len(n.args) == 1 and not n.keywords \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id in _RECEIVERS:
            yield n, n.func.attr


class SpanNotContextManaged(ModuleRule):
    id = "JT-TRACE-001"
    doc = ("a span created outside a `with` statement — it never "
           "records on exceptions and breaks the exporter's pairing")
    hint = "use `with trace.span(name, **args): ...`"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel.endswith(_TRACE_FILE):
            return
        with_exprs: set[int] = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    with_exprs.add(id(item.context_expr))
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "span" \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id in _RECEIVERS \
                    and id(n) not in with_exprs:
                yield self.finding(ctx, n,
                                   "span() not used as a context manager")


class UndeclaredMetricName(ModuleRule):
    id = "JT-TRACE-002"
    doc = ("counter/gauge/histogram name not in the declared registry "
           "(trace.DECLARED_METRICS) — a typo silently forks a "
           "metrics series")
    hint = ("declare the name in trace.DECLARED_METRICS (or fix the "
            "typo); dynamic names must start with a declared "
            "METRIC_PREFIXES entry")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel.endswith(_TRACE_FILE):
            return
        from .. import trace
        declared = trace.DECLARED_METRICS
        prefixes = trace.METRIC_PREFIXES
        all_names = frozenset().union(*declared.values())
        for call, kind in _metric_calls(ctx.tree):
            arg = call.args[0]
            name = const_str(arg)
            if name is not None:
                if name in declared[_METRIC_KINDS[kind]]:
                    continue
                if name in all_names:
                    yield self.finding(
                        ctx, call,
                        f"{name!r} is declared as a different metric "
                        f"kind than {kind}")
                elif any(name.startswith(p) for p in prefixes):
                    continue
                else:
                    yield self.finding(
                        ctx, call, f"undeclared {kind} name {name!r}")
            elif isinstance(arg, ast.JoinedStr):
                lead = arg.values[0] if arg.values else None
                lit = const_str(lead) if lead is not None else None
                if lit is None or not any(lit.startswith(p) or
                                          p.startswith(lit)
                                          for p in prefixes):
                    yield self.finding(
                        ctx, call,
                        f"dynamic {kind} name without a declared "
                        "prefix")
            # non-literal names (pass-through aggregation) are out of
            # lexical reach — runtime owns those


RULES = [SpanNotContextManaged(), UndeclaredMetricName()]
