"""JT-TRACE — tracer/span, metric-name, obs-event and trace-spool
discipline.

Spans must be context-managed (`with trace.span(...)`): a span object
held open across an exception never records, and manual enter/exit
splits the pairing the Chrome exporter depends on. Counter/gauge/
histogram names must come from the declared registry in
`jepsen_tpu.trace` (`DECLARED_METRICS` / `METRIC_PREFIXES`): the
metrics surface is keyed by string, so one typo silently forks a
series (`quarantined` vs `quarentined`) and every dashboard/bench
diff downstream reads half the events. Flight-recorder events must go
through the typed `obs.emit` API with a kind declared in
`obs.events.EVENT_KINDS` — an ad-hoc dict append to `events.jsonl`
(or a typoed kind) forks the event stream exactly the way an
undeclared metric forks a series. Worker trace spools
(`trace-<pid>.jsonl`) are a wire format owned end to end by
`jepsen_tpu.trace` (writer, loader, merger): a module hand-rolling
the path or the line format forks the spool protocol the same way —
the merge would silently skip (or mis-parse) its files.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, const_str

_TRACE_FILE = "jepsen_tpu/trace.py"
_RECEIVERS = {"trace", "tr", "tracer", "jtrace"}
_METRIC_KINDS = {"counter": "counters", "gauge": "gauges",
                 "histogram": "histograms"}


def _metric_calls(tree: ast.AST) -> Iterator[tuple[ast.Call, str]]:
    """(call, kind) for tracer metric constructor calls with exactly
    one positional argument on a tracer-ish receiver."""
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _METRIC_KINDS \
                and len(n.args) == 1 and not n.keywords \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id in _RECEIVERS:
            yield n, n.func.attr


class SpanNotContextManaged(ModuleRule):
    id = "JT-TRACE-001"
    doc = ("a span created outside a `with` statement — it never "
           "records on exceptions and breaks the exporter's pairing")
    hint = "use `with trace.span(name, **args): ...`"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel.endswith(_TRACE_FILE):
            return
        with_exprs: set[int] = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    with_exprs.add(id(item.context_expr))
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "span" \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id in _RECEIVERS \
                    and id(n) not in with_exprs:
                yield self.finding(ctx, n,
                                   "span() not used as a context manager")


class UndeclaredMetricName(ModuleRule):
    id = "JT-TRACE-002"
    doc = ("counter/gauge/histogram name not in the declared registry "
           "(trace.DECLARED_METRICS) — a typo silently forks a "
           "metrics series")
    hint = ("declare the name in trace.DECLARED_METRICS (or fix the "
            "typo); dynamic names must start with a declared "
            "METRIC_PREFIXES entry")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel.endswith(_TRACE_FILE):
            return
        from .. import trace
        declared = trace.DECLARED_METRICS
        prefixes = trace.METRIC_PREFIXES
        all_names = frozenset().union(*declared.values())
        for call, kind in _metric_calls(ctx.tree):
            arg = call.args[0]
            name = const_str(arg)
            if name is not None:
                if name in declared[_METRIC_KINDS[kind]]:
                    continue
                if name in all_names:
                    yield self.finding(
                        ctx, call,
                        f"{name!r} is declared as a different metric "
                        f"kind than {kind}")
                elif any(name.startswith(p) for p in prefixes):
                    continue
                else:
                    yield self.finding(
                        ctx, call, f"undeclared {kind} name {name!r}")
            elif isinstance(arg, ast.JoinedStr):
                lead = arg.values[0] if arg.values else None
                lit = const_str(lead) if lead is not None else None
                if lit is None or not any(lit.startswith(p) or
                                          p.startswith(lit)
                                          for p in prefixes):
                    yield self.finding(
                        ctx, call,
                        f"dynamic {kind} name without a declared "
                        "prefix")
            # non-literal names (pass-through aggregation) are out of
            # lexical reach — runtime owns those


_EVENTS_FILE = "jepsen_tpu/obs/events.py"


def _is_emit_call(n: ast.Call) -> bool:
    """Any `*.emit(...)` or bare `emit(...)` call — receiver-agnostic,
    so `from ..obs.events import emit` and aliased chains can't evade
    the kind check (the runtime raises ValueError on an undeclared
    kind, so an evading typo would be a production crash, not a lint
    finding). Only calls whose first argument is a STRING LITERAL are
    considered, which excludes every unrelated local `emit` helper in
    the tree."""
    f = n.func
    return ((isinstance(f, ast.Attribute) and f.attr == "emit")
            or (isinstance(f, ast.Name) and f.id == "emit"))


class AdHocObsEvent(ModuleRule):
    id = "JT-TRACE-003"
    doc = ("flight-recorder events must be emitted via the typed "
           "obs.emit API with a declared kind — ad-hoc events.jsonl "
           "writes (or a typoed kind) fork the event stream")
    hint = ("call obs.emit(<kind>, **fields); declare new kinds in "
            "jepsen_tpu/obs/events.py EVENT_KINDS")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel.endswith(_EVENTS_FILE):
            return
        from ..obs.events import EVENT_KINDS
        for n in ast.walk(ctx.tree):
            # the file name is private to obs/events.py: any other
            # module naming the path is building an ad-hoc writer
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and (n.value == "events.jsonl"           # jt-lint: ok JT-TRACE-003 (the rule's own match literal)
                         or n.value.endswith("/events.jsonl")):   # jt-lint: ok JT-TRACE-003 (the rule's own match literal)
                yield self.finding(
                    ctx, n, "ad-hoc events.jsonl path — the flight "
                            "recorder is written only by obs.events")
            elif isinstance(n, ast.Call) and _is_emit_call(n) \
                    and n.args:
                kind = const_str(n.args[0])
                if kind is not None and kind not in EVENT_KINDS:
                    yield self.finding(
                        ctx, n, f"undeclared obs event kind {kind!r}")


#: The spool-name shape trace.py owns (SPOOL_PREFIX + "<pid>.jsonl",
#: or the glob over it). Matches "trace-123.jsonl", "trace-*.jsonl"
#: and path-suffixed forms like "store/trace-9.jsonl".
_SPOOL_RE = re.compile(r"(^|/)trace-[^/]*\.jsonl$")


class AdHocSpoolWrite(ModuleRule):
    id = "JT-TRACE-004"
    doc = ("a `trace-<pid>.jsonl` worker-spool path built outside "
           "jepsen_tpu.trace — the spool naming and line format are "
           "a wire protocol owned by trace.py; an ad-hoc writer or "
           "globber forks it and the merge silently skips its files")
    hint = ("go through the trace API (worker_ctx/ensure_worker_"
            "tracer/flush_worker_spool to write, merge_traces/"
            "iter_spools/load_spool/clean_spools to read — the "
            "naming lives in trace.SPOOL_PREFIX)")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel.endswith(_TRACE_FILE):
            return
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                if _SPOOL_RE.search(n.value):
                    yield self.finding(
                        ctx, n, f"ad-hoc spool path {n.value!r}")
            elif isinstance(n, ast.JoinedStr) and n.values:
                parts = [const_str(v) for v in n.values]
                tail = parts[-1]
                # any constant segment ending in a path component that
                # starts "trace-" (covers both f"trace-{pid}.jsonl"
                # and f"{store}/trace-{pid}.jsonl"), with the literal
                # ".jsonl" tail — an interpolated directory prefix
                # can't evade the rule
                if tail is not None and tail.endswith(".jsonl") \
                        and any(p is not None
                                and re.search(r"(^|/)trace-[^/]*$", p)
                                for p in parts[:-1]):
                    yield self.finding(
                        ctx, n, "ad-hoc f-string spool path")


RULES = [SpanNotContextManaged(), UndeclaredMetricName(),
         AdHocObsEvent(), AdHocSpoolWrite()]
