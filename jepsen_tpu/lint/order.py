"""JT-ORD — path-sensitive happens-before prover for the serve
fleet's ordering protocol.

PR-14/19's multi-daemon verdict service is correct only because of
*ordering*: the journal append happens before the reply frame, the
epoch fence is read between a fold's dispatch and its journal write,
failover bumps the epoch on disk before STONITH before adoption, a
donated device slot is released on every exit path, admission closes
under its condition variable and before the draining flag becomes
observable. Until now those invariants lived in comments and smoke
tests. These rules prove them statically: each contract in
`contracts.ORDER_CONTRACTS` names a function, two (or three) marker
statements, and a path property, and the prover decides it on the
function's CFG (`cfg.py` — `finally` bodies routed on abnormal
exits, branch polarity recorded):

  * ``dominates``      — removal search from the entry: can the
    second marker be reached without passing the first? A singleton
    first-site is fast-pathed through the classic block-level
    `dominators` solve; the removal search is the decider.
  * ``postdominates``  — removal search from each first site toward
    `cfg.exit` (exception edges included), `post_dominators` as the
    fast path.
  * ``between`` / ``never-after`` — the same searches anchored at
    the first marker's sites.
  * ``under-lock``     — `compute_locksets` with a resolver that
    names ANY dotted with-item (`self._cv` included), then a
    MUST-held check at the marker.

A contract whose function or marker no longer matches anything is
itself a finding ("anchor vanished") — a rename cannot silently turn
a proof into a no-op. The mutation harness
(tests/test_order_prover.py) seeds one ordering bug per rule into a
copy of the real serve/fleet modules and pins exactly the expected
finding; the unmutated tree and the live repo are pinned clean.

Soundness notes: guard pruning (`OrderContract.guard`) skips the
false arm of ``if <guard>:`` only when the flag is assigned exactly
once in the function — otherwise the search stays fully
conservative. A statement matching both the kill and the target
marker counts as the kill (no false positive from unknowable
intra-statement order). Frames built outside a ``{op=...}`` marker's
dict literal stay unmatched on purpose: the marker names a specific
emission site.
"""

from __future__ import annotations

import ast
from collections import deque
from fnmatch import fnmatchcase
from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, dotted
from . import cfg as cfg_mod
from . import contracts

__all__ = ["RULES"]


# ---------------------------------------------------------------------------
# Markers
# ---------------------------------------------------------------------------

def _dotted_loose(node: ast.AST) -> str | None:
    """`a.b.c` with subscript links rendered `[]`: the callee of
    ``ent["journal"].record(...)`` is ``ent[].record``, so a glob can
    anchor on the method without caring which key was indexed."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted_loose(node.value)
        return None if base is None else f"{base}.{node.attr}"
    if isinstance(node, ast.Subscript):
        base = _dotted_loose(node.value)
        return None if base is None else f"{base}[]"
    return None


class _Marker:
    """One parsed ORDER_CONTRACTS marker (syntax in contracts.py)."""

    def __init__(self, spec: str):
        self.spec = spec
        self.op: str | None = None
        if spec.startswith("call:"):
            self.kind = "call"
            body = spec[len("call:"):]
            if body.endswith("}") and "{op=" in body:
                body, _, rest = body.rpartition("{op=")
                self.op = rest[:-1]
            self.glob = body
        elif spec.startswith("set:"):
            self.kind = "set"
            self.name = spec[len("set:"):]
        else:
            raise ValueError(f"bad ORDER_CONTRACTS marker {spec!r}")

    def matches(self, s: ast.stmt) -> bool:
        if self.kind == "set":
            if not isinstance(s, (ast.Assign, ast.AugAssign,
                                  ast.AnnAssign)):
                return False
            targets = s.targets if isinstance(s, ast.Assign) \
                else [s.target]
            for t in targets:
                nm = t.attr if isinstance(t, ast.Attribute) else (
                    t.id if isinstance(t, ast.Name) else None)
                if nm == self.name:
                    return True
            return False
        for h in _header_nodes(s):
            for n in ast.walk(h):
                if isinstance(n, ast.Call):
                    d = _dotted_loose(n.func)
                    if d is not None and fnmatchcase(d, self.glob) \
                            and (self.op is None
                                 or _has_op_literal(n, self.op)):
                        return True
        return False


def _header_nodes(s: ast.stmt) -> list[ast.AST]:
    """What a marker may match on: compound statements expose only
    their HEADER (the test/iter/with-items the block executes at that
    point) — their bodies are separate CFG instructions."""
    if isinstance(s, (ast.If, ast.While)):
        return [s.test]
    if isinstance(s, (ast.For, ast.AsyncFor)):
        return [s.target, s.iter]
    if isinstance(s, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in s.items]
    if isinstance(s, ast.Try):
        return []
    return [s]


def _has_op_literal(call: ast.Call, op: str) -> bool:
    for a in call.args:
        if isinstance(a, ast.Dict):
            for k, v in zip(a.keys, a.values):
                if isinstance(k, ast.Constant) and k.value == "op" \
                        and isinstance(v, ast.Constant) \
                        and v.value == op:
                    return True
    return False


# ---------------------------------------------------------------------------
# Per-function graphs (memoized on the ModuleCtx)
# ---------------------------------------------------------------------------

def _lock_of(expr: ast.AST) -> str | None:
    """Every dotted with-item is a lock id here — under-lock
    contracts name the attribute (`self._cv`) directly, and a
    non-lock context manager spelled as a call (`tr.span(...)`)
    renders None, so nothing is guessed."""
    return dotted(expr)


class _FuncGraph:
    def __init__(self, node: ast.AST):
        self.node = node
        self.cfg = cfg_mod.build_cfg(node, _lock_of)
        self._locksets: dict | None = None
        self._dom: dict | None = None
        self._pdom: dict | None = None
        self._assign_counts: dict[str, int] = {}

    def locksets(self) -> dict:
        if self._locksets is None:
            self._locksets = cfg_mod.compute_locksets(self.cfg)
        return self._locksets

    def dom(self) -> dict:
        if self._dom is None:
            self._dom = cfg_mod.dominators(self.cfg)
        return self._dom

    def pdom(self) -> dict:
        if self._pdom is None:
            self._pdom = cfg_mod.post_dominators(self.cfg)
        return self._pdom

    def prunable_guard(self, name: str) -> bool:
        """Pruning `if <name>:` false arms is sound only when the
        flag has exactly one assignment in the function (it cannot
        change between the guarded acquire and the guarded release)."""
        n = self._assign_counts.get(name)
        if n is None:
            n = 0
            for sub in ast.walk(self.node):
                if isinstance(sub, ast.Assign):
                    tgts = sub.targets
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    tgts = [sub.target]
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    tgts = [sub.target]
                else:
                    continue
                for t in tgts:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name) \
                                and leaf.id == name:
                            n += 1
            self._assign_counts[name] = n
        return n == 1

    def occurrences(self, m: _Marker) -> list[tuple[int, int]]:
        """(block id, instruction index) of every statement the
        marker matches — finally-copy duplicates included."""
        occ = []
        for b in self.cfg.blocks.values():
            for i, ins in enumerate(b.instrs):
                if ins[0] == "stmt" and m.matches(ins[1]):
                    occ.append((b.id, i))
        return occ


def _functions(ctx: ModuleCtx) -> dict[str, ast.AST]:
    funcs = getattr(ctx, "_order_funcs", None)
    if funcs is None:
        funcs = {q: node for q, _c, node in cfg_mod.iter_defs(ctx.tree)}
        ctx._order_funcs = funcs
    return funcs


def _graph(ctx: ModuleCtx, qual: str, node: ast.AST) -> _FuncGraph:
    cache = getattr(ctx, "_order_graphs", None)
    if cache is None:
        cache = {}
        ctx._order_graphs = cache
    g = cache.get(qual)
    if g is None:
        g = _FuncGraph(node)
        cache[qual] = g
    return g


# ---------------------------------------------------------------------------
# The path searches
# ---------------------------------------------------------------------------

def _succs(g: _FuncGraph, bid: int, guard: str) -> list[int]:
    b = g.cfg.blocks[bid]
    if guard and bid in g.cfg.branches and b.instrs:
        last = b.instrs[-1]
        if last[0] == "stmt" and isinstance(last[1], ast.If):
            t = last[1].test
            if isinstance(t, ast.Name) and t.id == guard \
                    and g.prunable_guard(guard):
                _then, els = g.cfg.branches[bid]
                return [s for s in b.succs if s != els]
    return list(b.succs)


def _scan(b, i0: int, kill: _Marker | None, hit: _Marker | None):
    """Walk a block's instructions from i0: ('hit', stmt) when the
    target marker is reached, ('kill', None) when the kill marker
    blocks the path first, ('fall', None) when the block runs off its
    end. A statement matching both counts as the kill."""
    for ins in b.instrs[i0:]:
        if ins[0] != "stmt":
            continue
        s = ins[1]
        if kill is not None and kill.matches(s):
            return ("kill", None)
        if hit is not None and hit.matches(s):
            return ("hit", s)
    return ("fall", None)


def _reach(g: _FuncGraph, starts: list[tuple[int, int]],
           kill: _Marker | None, hit: _Marker | None,
           guard: str = "", to_exit: bool = False):
    """The removal search: from the start positions, can a path reach
    a `hit` site (or `cfg.exit` when `to_exit`) without first passing
    a `kill` site? Returns the witnessing statement (or True for an
    exit reach), else None — None means the contract HOLDS."""
    q: deque[int] = deque()
    seen: set[int] = set()

    def expand(bid: int) -> None:
        for nb in _succs(g, bid, guard):
            if nb not in seen:
                seen.add(nb)
                q.append(nb)

    for bid, i0 in starts:
        st, s = _scan(g.cfg.blocks[bid], i0, kill, hit)
        if st == "hit":
            return s
        if st == "fall":
            expand(bid)
    while q:
        bid = q.popleft()
        if to_exit and bid == g.cfg.exit:
            return True
        st, s = _scan(g.cfg.blocks[bid], 0, kill, hit)
        if st == "hit":
            return s
        if st == "fall":
            expand(bid)
    return None


def _block_dominates(g: _FuncGraph, first: list[tuple[int, int]],
                     second: list[tuple[int, int]]) -> bool:
    """Block-level fast path: a SINGLE first site whose block
    dominates every second site (intra-block order checked when they
    share a block) proves the contract without the removal search.
    Only ever returns a positive proof — the removal search decides
    the rest."""
    blocks = {b for b, _i in first}
    if len(blocks) != 1:
        return False
    fb = next(iter(blocks))
    fi = min(i for b, i in first if b == fb)
    dom = g.dom()
    for sb, si in second:
        if fb not in dom[sb]:
            return False
        if sb == fb and si < fi:
            return False
    return True


def _block_postdominates(g: _FuncGraph, first: list[tuple[int, int]],
                         second: list[tuple[int, int]]) -> bool:
    blocks = {b for b, _i in second}
    if len(blocks) != 1:
        return False
    sb = next(iter(blocks))
    si = max(i for b, i in second if b == sb)
    pdom = g.pdom()
    for fb, fi in first:
        if sb not in pdom[fb]:
            return False
        if fb == sb and si < fi:
            return False
    return True


def _after(occ: list[tuple[int, int]]) -> list[tuple[int, int]]:
    return [(b, i + 1) for b, i in occ]


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

class OrderRule(ModuleRule):
    """One JT-ORD id = every ORDER_CONTRACTS entry carrying it. The
    registry names the file, so a rule only fires on its module (and
    on fixture copies laid out under the same relative path)."""

    def __init__(self, rid: str, doc: str, hint: str):
        self.id = rid
        self.doc = doc
        self.hint = hint

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for c in contracts.ORDER_CONTRACTS:
            if c.rule != self.id or c.file != ctx.rel:
                continue
            yield from self._check(ctx, c)

    def _check(self, ctx: ModuleCtx,
               c: "contracts.OrderContract") -> Iterator[Finding]:
        fn = _functions(ctx).get(c.func)
        if fn is None:
            yield self.finding(
                ctx, 1,
                f"ORDER_CONTRACTS anchor vanished: function "
                f"{c.func!r} not found — re-anchor the {c.kind} "
                f"contract ({c.doc})")
            return
        g = _graph(ctx, c.func, fn)
        roles = [("first", c.first)]
        if c.mid:
            roles.append(("mid", c.mid))
        if c.second:
            roles.append(("second", c.second))
        occ: dict[str, tuple[_Marker, list]] = {}
        vanished = False
        for role, spec in roles:
            m = _Marker(spec)
            o = g.occurrences(m)
            if not o:
                vanished = True
                yield self.finding(
                    ctx, fn,
                    f"ORDER_CONTRACTS anchor vanished: {role} marker "
                    f"{spec!r} matches nothing in {c.func} — "
                    f"re-anchor the {c.kind} contract")
            occ[role] = (m, o)
        if vanished:
            return

        first_m, first_o = occ["first"]
        if c.kind == "dominates":
            second_m, second_o = occ["second"]
            if _block_dominates(g, first_o, second_o):
                return
            w = _reach(g, [(g.cfg.entry, 0)], first_m, second_m,
                       guard=c.guard)
            if w is not None:
                yield self.finding(
                    ctx, w,
                    f"{c.first!r} does not dominate {c.second!r} in "
                    f"{c.func}: a path reaches this {c.second} site "
                    f"without passing {c.first} — {c.doc}")
        elif c.kind == "postdominates":
            second_m, second_o = occ["second"]
            if _block_postdominates(g, first_o, second_o):
                return
            w = _reach(g, _after(first_o), second_m, None,
                       guard=c.guard, to_exit=True)
            if w is not None:
                yield self.finding(
                    ctx, fn,
                    f"{c.second!r} does not post-dominate "
                    f"{c.first!r} in {c.func}: an exit path leaves "
                    f"{c.first} without passing {c.second} — {c.doc}")
        elif c.kind == "between":
            mid_m, _mid_o = occ["mid"]
            second_m, _second_o = occ["second"]
            w = _reach(g, _after(first_o), mid_m, second_m,
                       guard=c.guard)
            if w is not None:
                yield self.finding(
                    ctx, w,
                    f"{c.mid!r} is not on every {c.first!r} → "
                    f"{c.second!r} path in {c.func}: this "
                    f"{c.second} site is reachable from {c.first} "
                    f"without passing {c.mid} — {c.doc}")
        elif c.kind == "never-after":
            second_m, _second_o = occ["second"]
            w = _reach(g, _after(first_o), None, second_m,
                       guard=c.guard)
            if w is not None:
                yield self.finding(
                    ctx, w,
                    f"{c.second!r} is reachable after {c.first!r} in "
                    f"{c.func} — {c.doc}")
        elif c.kind == "under-lock":
            locks = g.locksets()
            for b, i in first_o:
                s = g.cfg.blocks[b].instrs[i][1]
                held = locks.get(id(s), frozenset())
                if c.lock not in held:
                    yield self.finding(
                        ctx, s,
                        f"{c.first!r} executes without {c.lock!r} "
                        f"MUST-held in {c.func} (held: "
                        f"{sorted(held) or 'nothing'}) — {c.doc}")
        else:
            yield self.finding(
                ctx, fn,
                f"ORDER_CONTRACTS entry has unknown kind {c.kind!r}")


RULES = [
    OrderRule(
        "JT-ORD-001",
        doc=("journal-then-reply: in the daemon's verdict path the "
             "journal append dominates every reply-frame send — an "
             "ack can only name a verdict the journal already holds"),
        hint=("journal the verdict (or explicitly flag journaled: "
              "false on the frame) before any conn.send on the "
              "verdict path")),
    OrderRule(
        "JT-ORD-002",
        doc=("the zombie fence: the epoch-fence read lies between a "
             "fold's dispatch and its journal write on every path, "
             "and the fenced drain path never reaches the journal"),
        hint=("check self._fenced() after dispatch and before "
              "journaling; a fenced fold must drain and drop, never "
              "journal")),
    OrderRule(
        "JT-ORD-003",
        doc=("failover ordering: the epoch bump is durably published "
             "(temp+os.replace) before STONITH, STONITH before "
             "tenant adoption, and never STONITH after adoption"),
        hint=("keep _fail_over's fence → STONITH → adopt+resend "
              "sequence; the fence must hit disk first"),),
    OrderRule(
        "JT-ORD-004",
        doc=("no leaked device slot: DeviceSlots release "
             "post-dominates the donation acquire on every exit "
             "path, exception edges included"),
        hint=("release the donated slot in a finally (or on every "
              "raise path) so a checker crash cannot strand the "
              "slot")),
    OrderRule(
        "JT-ORD-005",
        doc=("drain close ordering: admission closes under its "
             "condition variable, and before the draining flag "
             "becomes observable to the scheduler"),
        hint=("mutate Admission state only under self._cv, and call "
              "admission.close() before _draining.set() in "
              "request_drain")),
]
