"""Lightweight parser over the native C++ sources (JT-ABI's C side).

This is NOT a C++ front end — it extracts exactly the ABI surface the
ctypes loader and the sidecar readers depend on, from source shaped
like ours (clang-formatted, `extern "C"` exports, `static constexpr`
layout constants):

  * exported `jt_*` signatures (name, normalized return type,
    normalized arg types) from every `extern "C"` region;
  * the literal each `jt_*_abi_version()` returns;
  * integer layout constants (`static constexpr ... NAME = expr;`
    with a tiny safe evaluator for `64 * 1024` / `int64_t(1) << 30`);
  * the sidecar MAGIC byte-string variants (ternary arms expanded);
  * the sidecar field-write order (`arrays.push_back({"name", ...})`
    in source order).

Everything degrades to "absent" rather than guessing: a construct the
parser can't read yields no value, and the cross-check rules treat a
missing value as unprovable, not as drift. The one exception is an
`extern "C"` region with NO parseable exports — that is reported by
the caller, since it means the parser (not the code) went blind.
"""

from __future__ import annotations

import ast
import itertools
import re
from dataclasses import dataclass, field

__all__ = [
    "CSig", "NativeABI", "parse_native", "normalize_type",
    "safe_int_eval",
]


@dataclass(frozen=True)
class CSig:
    """One exported C function: normalized types, no arg names."""

    name: str
    ret: str
    args: tuple[str, ...]
    line: int


@dataclass
class NativeABI:
    """Everything JT-ABI extracts from one .cc file."""

    path: str = ""
    exports: dict[str, CSig] = field(default_factory=dict)
    abi_versions: dict[str, int] = field(default_factory=dict)
    constants: dict[str, int] = field(default_factory=dict)
    magics: set[bytes] = field(default_factory=set)
    sidecar_fields: tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# Comments and small helpers
# ---------------------------------------------------------------------------

def strip_comments(text: str) -> str:
    """// and /* */ comments replaced by spaces, preserving newlines
    (so line numbers computed on the stripped text stay true)."""
    out: list[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '"' or c == "'":
            q = c
            out.append(c)
            i += 1
            while i < n:
                out.append(text[i])
                if text[i] == "\\" and i + 1 < n:
                    out.append(text[i + 1])
                    i += 2
                    continue
                if text[i] == q:
                    i += 1
                    break
                i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                out.append(" ")
                i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            while i + 1 < n and not (text[i] == "*"
                                     and text[i + 1] == "/"):
                out.append("\n" if text[i] == "\n" else " ")
                i += 1
            out.append("  ")
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def normalize_type(decl: str, *, with_name: bool = False) -> str | None:
    """`const char* hist_path` → 'char*'; `int64_t out[8]` → 'int64_t*';
    `void` → None (empty arg list). `with_name=False` treats the whole
    string as a type (return types)."""
    decl = decl.strip()
    if not decl or decl == "void":
        return None if with_name else "void"
    stars = decl.count("*") + (1 if "[" in decl else 0)
    decl = re.sub(r"\[[^\]]*\]", " ", decl)
    toks = [t for t in decl.replace("*", " ").split()
            if t not in ("const", "struct")]
    if with_name and len(toks) > 1:
        toks = toks[:-1]     # drop the parameter name
    return " ".join(toks) + "*" * stars


_SUFFIX_RE = re.compile(r"(?<=[0-9a-fA-F])(?:[uU]?[lL]{1,2}|[uU])\b")
_CAST_RE = re.compile(r"\b(?:u?int(?:8|16|32|64)_t|size_t|long|int)\s*\(")


def safe_int_eval(expr: str) -> int | None:
    """Evaluate a constant integer expression (`64 * 1024`,
    `int64_t(1) << 30`, `0x9E37...ULL`) via a whitelisted AST walk;
    None for anything else (INT64_MIN, arithmetic we don't model)."""
    expr = _SUFFIX_RE.sub("", expr)
    expr = _CAST_RE.sub("(", expr)
    try:
        tree = ast.parse(expr.strip(), mode="eval")
    except SyntaxError:
        return None

    def ev(n: ast.AST) -> int:
        if isinstance(n, ast.Expression):
            return ev(n.body)
        if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                and not isinstance(n.value, bool):
            return n.value
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.USub):
            return -ev(n.operand)
        if isinstance(n, ast.BinOp):
            ops = {ast.Mult: lambda a, b: a * b,
                   ast.Add: lambda a, b: a + b,
                   ast.Sub: lambda a, b: a - b,
                   ast.LShift: lambda a, b: a << b,
                   ast.RShift: lambda a, b: a >> b,
                   ast.BitOr: lambda a, b: a | b,
                   ast.FloorDiv: lambda a, b: a // b}
            f = ops.get(type(n.op))
            if f is None:
                raise ValueError(ast.dump(n.op))
            return f(ev(n.left), ev(n.right))
        raise ValueError(ast.dump(n))

    try:
        return ev(tree)
    except (ValueError, ZeroDivisionError, RecursionError):
        return None


# ---------------------------------------------------------------------------
# extern "C" regions and exported signatures
# ---------------------------------------------------------------------------

def _extern_c_regions(text: str) -> list[tuple[int, int]]:
    """(start, end) character spans of each `extern "C" { ... }` body,
    by brace matching."""
    regions = []
    for m in re.finditer(r'extern\s+"C"\s*\{', text):
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        regions.append((m.end(), i - 1))
    return regions


_FN_RE = re.compile(
    r"(?P<ret>[A-Za-z_][A-Za-z0-9_ \t]*?[\s\*]+)"
    r"(?P<name>jt_[A-Za-z0-9_]+)\s*\((?P<args>[^)]*)\)\s*\{")


def _parse_exports(text: str) -> dict[str, CSig]:
    out: dict[str, CSig] = {}
    for start, end in _extern_c_regions(text):
        body = text[start:end]
        for m in _FN_RE.finditer(body):
            ret = normalize_type(m.group("ret"))
            args = []
            raw = m.group("args").strip()
            if raw:
                for piece in raw.split(","):
                    t = normalize_type(piece, with_name=True)
                    if t is not None:
                        args.append(t)
            line = text[:start + m.start()].count("\n") + 1
            name = m.group("name")
            out[name] = CSig(name, ret or "void", tuple(args), line)
    return out


_VERSION_RE = re.compile(
    r"\b(jt_[A-Za-z0-9_]*abi_version)\s*\(\s*\)\s*\{\s*return\s+(\d+)")

_CONST_RE = re.compile(
    r"\bstatic\s+constexpr\s+[A-Za-z_][A-Za-z0-9_]*\s+"
    r"([A-Za-z_][A-Za-z0-9_]*)\s*=\s*([^;]+);")

_MAGIC_RE = re.compile(
    r"\bconst\s+char\s+MAGIC\s*\[\s*\d+\s*\]\s*=\s*\{([^}]*)\}")

_PUSH_RE = re.compile(r'arrays\.push_back\(\s*\{\s*"(\w+)"')

_CHAR_RE = re.compile(r"'(\\?[^'])'")


def _magic_variants(elems_src: str) -> set[bytes]:
    """Expand the MAGIC initializer into its possible byte strings —
    each element is a char literal or a ternary over two of them."""
    per_elem: list[list[bytes]] = []
    for piece in elems_src.split(","):
        chars = [c.encode().decode("unicode_escape").encode("latin-1")
                 for c in _CHAR_RE.findall(piece)]
        if not chars:
            return set()    # un-modeled element: give up, not guess
        per_elem.append(chars if "?" in piece else chars[:1])
    return {b"".join(combo)
            for combo in itertools.product(*per_elem)}


def parse_native(text: str, path: str = "") -> NativeABI:
    """The full JT-ABI extraction for one .cc source text."""
    stripped = strip_comments(text)
    abi = NativeABI(path=path)
    abi.exports = _parse_exports(stripped)
    for m in _VERSION_RE.finditer(stripped):
        abi.abi_versions[m.group(1)] = int(m.group(2))
    for m in _CONST_RE.finditer(stripped):
        v = safe_int_eval(m.group(2))
        if v is not None:
            abi.constants.setdefault(m.group(1), v)
    mm = _MAGIC_RE.search(stripped)
    if mm:
        abi.magics = _magic_variants(mm.group(1))
    # canonical field write order: the v1/v2 branches push the same
    # field name at the same relative position, so first occurrence
    # IS the order — and keeps a reordered reader from hiding behind
    # the duplicate
    seen: list[str] = []
    for m in _PUSH_RE.finditer(stripped):
        if m.group(1) not in seen:
            seen.append(m.group(1))
    abi.sidecar_fields = tuple(seen)
    return abi
