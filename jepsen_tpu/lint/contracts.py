"""The declared-contracts registry the cross-boundary analyses check
against.

`gates.py` proved the pattern: an invariant written down ONCE, in a
typed table, is an invariant the linter can enforce everywhere it is
consumed. This module does the same for the encode→pack→dispatch
tensor contracts (JT-TENSOR), the lock/shared-state discipline of the
sweep's thread graph (JT-LOCK), the hot-path scoping both share, the
store-artifact durability protocols (JT-DUR) — every on-disk
format a sweep persists, declared once with its crash-consistency
protocol, sanctioned writer/reader helpers and retention class —
and the serve fleet's happens-before protocol (JT-ORD): the
journal-then-reply, fence-between-dispatch-and-journal and
failover-ordering contracts, declared once and proved
path-sensitively against the cfg.py graphs.
The ABI/layout contracts (JT-ABI) are NOT declared here — their source
of truth is `native/hist_encode.cc` itself, parsed by `cparse.py` and
cross-checked against `native_lib.py`/`store.py`; duplicating them in
a third place would just add one more thing to drift.

Every table is consumed by a rule in `rules_tensor.py` /
`rules_lock.py` / `rules_dur.py` / `order.py`; tests/test_lint.py
pins the registry's shape so an entry can't silently vanish.
"""

from __future__ import annotations

from dataclasses import dataclass
from fnmatch import fnmatchcase

# ---------------------------------------------------------------------------
# JT-TENSOR — dtype/shape/fill contracts of the encode→pack→dispatch path
# ---------------------------------------------------------------------------

#: Canonical dtype per encoded-tensor field (numpy dtype names). The
#: lean int64 index tensors and the int32 device (`d_*`) tensors are
#: both declared — the narrowing between them is explicit below, so an
#: UNdeclared cast anywhere on the path is a finding.
TENSOR_DTYPES: dict[str, str] = {
    "appends": "int32",
    "reads": "int32",
    "edges": "int32",
    "status": "int32",
    "process": "int32",
    "invoke_index": "int64",
    "complete_index": "int64",
    "d_invoke": "int32",
    "d_complete": "int32",
    "n_txns": "int32",
    "kid_to_pre": "int32",
}

#: Common local-variable spellings of the declared fields (the packers
#: shorten `*_index` to `*_idx`); dataflow tags resolve through this.
FIELD_ALIASES: dict[str, str] = {
    "invoke_idx": "invoke_index",
    "complete_idx": "complete_index",
}

#: Sanctioned narrowings: (source field, destination dtype). The v2
#: sidecar's device tensors are the int32 narrowing of the lean int64
#: index tensors — declared here because both writers (store.py's
#: `_padded_arrays`, hist_encode.cc's `write_sidecar`) perform it; any
#: OTHER cast of a contracted tensor is drift.
DECLARED_NARROWINGS: frozenset[tuple[str, str]] = frozenset({
    ("invoke_index", "int32"),
    ("complete_index", "int32"),
})

#: pack_batch's fill convention: dead triple/process rows are -1 (no
#: txn, no key), dead index rows 0. A `np.full` building a contracted
#: tensor with any other fill silently corrupts the kernel's masking.
FILL_VALUES: dict[str, int] = {
    "appends": -1,
    "reads": -1,
    "edges": -1,
    "process": -1,
    "invoke_index": 0,
    "complete_index": 0,
    "d_invoke": 0,
    "d_complete": 0,
    "n_txns": 0,
}

#: Fields whose minor axis is a triple — a reshape of one of these to
#: a literal shape must end in 3.
TRIPLE_FIELDS: frozenset[str] = frozenset({"appends", "reads", "edges"})

#: The bucket geometry: txn axis pads to the MXU tile, every minor
#: axis to 8 — kernels.BatchShape.plan, store.dispatch_pad_plan and
#: hist_encode.cc's pad_up all agree on these two numbers (JT-ABI-004
#: proves the native side; JT-TENSOR-003 flags any literal pad
#: multiple outside this set on the Python side).
PAD_TXNS = 128
PAD_MINOR = 8
PAD_MULTIPLES: frozenset[int] = frozenset({PAD_TXNS, PAD_MINOR})

#: Donated-arg positions of a single-device bucket dispatch: the six
#: packed input tensors, nothing else. `donate_argnums` anywhere in
#: the analyzed files must spell exactly this.
DONATE_ARGNUMS: tuple[int, ...] = (0, 1, 2, 3, 4, 5)

#: Files whose whole body is the pack/h2d hot path for the host-
#: materialization rule (JT-TENSOR-002, ex-JT-JAX-005).
HOT_PATH_FILES = ("jepsen_tpu/parallel/", "jepsen_tpu/shm.py")

#: Function-name shapes treated as hot-path regardless of file — the
#: packers and h2d stages (also what makes the rule fixture-testable).
HOT_FN_PREFIXES = ("pack_", "_h2d", "_prep_bucket", "shard_batch")

#: Files the tensor dataflow pass analyzes module-wide (beyond the
#: hot-path scoping above): everywhere contracted tensors are built,
#: persisted, or packed.
TENSOR_FILES = (
    "jepsen_tpu/checker/elle/kernels.py",
    "jepsen_tpu/checker/knossos/kernels.py",
    "jepsen_tpu/parallel/",
    "jepsen_tpu/shm.py",
    "jepsen_tpu/store.py",
)


def is_tensor_file(rel: str) -> bool:
    return any(t in rel for t in TENSOR_FILES)


def is_hot_path_file(rel: str) -> bool:
    return any(h in rel for h in HOT_PATH_FILES)


def field_of(name: str) -> str | None:
    """The declared field a local name refers to, or None."""
    name = FIELD_ALIASES.get(name, name)
    return name if name in TENSOR_DTYPES else None


# ---------------------------------------------------------------------------
# JT-LOCK — shared state, its guarding locks, and blocking calls
# ---------------------------------------------------------------------------

#: Shared mutable state and the lock that must be held to WRITE it:
#: (class name, attribute, lock). The lock is either a `self.<attr>`
#: spelled as the attr name, or a module-global lock name. Reads are
#: out of scope (the registry entries are all either monotonic
#: counters or snapshot-read-by-design); `__init__` is exempt
#: (construction is single-threaded by definition). These are exactly
#: the structures the PR-6/7 review passes found raced by hand: the
#: donated-slot ledger, the health snapshot's seq, the tracer's
#: metric cells.
SHARED_STATE: tuple[tuple[str, str, str], ...] = (
    ("DeviceSlotLedger", "_inflight", "_lock"),
    ("HealthSampler", "_seq", "_wlock"),
    ("Counter", "value", "_MLOCK"),
    ("Histogram", "count", "_MLOCK"),
    ("Histogram", "total", "_MLOCK"),
    ("Histogram", "min", "_MLOCK"),
    ("Histogram", "max", "_MLOCK"),
    ("_Injector", "_fired", "_lock"),
)

#: Calls that park the calling thread for unbounded/long time — doing
#: one while holding a lock starves every other waiter (the "gauge
#: published outside the lock" / "write_snapshot serialized" class,
#: inverted). Consumed by rules_lock._is_blocking in three forms:
#: exact dotted names, dotted-name prefixes, and attribute-call tails.
#: `.join()` is deliberately NOT here: the spelling is shared with
#: `str.join` (every f-string-averse formatter in the tree), and a
#: receiver-type analysis precise enough to split them doesn't fit a
#: lexical pass — thread joins under a lock surface via JT-LOCK-001's
#: call-graph edges instead when the joined worker takes locks.
BLOCKING_EXACT: frozenset[str] = frozenset({"time.sleep", "sleep"})
BLOCKING_PREFIXES: tuple[str, ...] = ("subprocess.",)
BLOCKING_METHOD_TAILS: frozenset[str] = frozenset({
    "block_until_ready",   # unbounded device wait
    "result",              # Future.result
})

#: Constructors whose instances are thread-safe by design: a Thread
#: target may share these with its spawner freely (JT-LOCK-004's
#: confinement rule skips them).
THREADSAFE_CTORS: frozenset[str] = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "Semaphore", "BoundedSemaphore", "Event", "Lock", "RLock",
    "Condition", "Barrier", "deque",
})


# ---------------------------------------------------------------------------
# JT-DUR — the store-artifact registry: every on-disk format a sweep
# persists, declared ONCE with its crash-consistency protocol.
# ---------------------------------------------------------------------------

#: The two-and-a-half durability protocols the package implements:
#:
#:   * `journal`  — append-only JSON lines, each record written as ONE
#:     `write()` and `flush()`ed as it lands; a crash tears at most
#:     the line in flight, which the reader skips and the next append
#:     seals (the VerdictJournal discipline).
#:   * `snapshot` — whole-file artifacts published via temp file +
#:     `os.replace` (`trace.atomic_write_text`): a reader sees the
#:     previous complete file or the new one, never bytes of both.
#:   * `spool`    — a journal owned by ONE process for ONE sweep,
#:     cleaned at the next sweep start (worker trace spools).
#:   * `marker`   — a tiny atomic pointer/flag (done markers, the
#:     latest/current symlinks): existence + content flip atomically.
#:   * `sidecar`  — a derived binary cache keyed by its source;
#:     written to a temp name and `os.replace`d, discarded (never
#:     trusted) on any mismatch.
PROTOCOLS = ("journal", "snapshot", "spool", "marker", "sidecar")

#: Declared retention classes — how an artifact is kept from growing
#: without bound. JT-DUR-005 requires every append-forever (journal/
#: spool) artifact to declare one:
#:
#:   * `rotated`        — size-capped, rotated by atomic rename
#:                        (events.jsonl under JEPSEN_TPU_EVENTS_MAX_BYTES);
#:   * `replaced`       — each write replaces the whole artifact;
#:   * `merged`         — periodically folded/deduplicated into one
#:                        file by a coordinator (per-shard costdbs);
#:   * `per-run`        — bounded by the run dir it lives in;
#:   * `per-sweep`      — cleared at the next sweep start;
#:   * `store-lifetime` — grows with the store; pruned only when the
#:                        store is recycled (verdict journals —
#:                        compaction is ROADMAP item 5).
RETENTION_CLASSES: frozenset[str] = frozenset({
    "rotated", "replaced", "merged", "per-run", "per-sweep",
    "store-lifetime",
})


@dataclass(frozen=True)
class StoreArtifact:
    """One declared on-disk artifact: where it lives, which protocol
    its writers/readers must speak, and who is sanctioned to speak it.
    `patterns` are fnmatch globs over the artifact's FILE NAME
    (store-root-relative for `root="store"`, compile-cache-relative
    for `root="cache"`, run-dir for the sidecars). `writers`/`readers`
    name the sanctioned helpers as `<module rel>:<qualname>`;
    `helpers` are path-constructor functions whose RETURN is this
    artifact's path — the fileflow pass resolves calls to them
    interprocedurally."""

    name: str
    patterns: tuple[str, ...]
    protocol: str
    writers: tuple[str, ...]
    readers: tuple[str, ...]
    retention: str | None
    doc: str
    root: str = "store"
    helpers: tuple[str, ...] = ()


STORE_ARTIFACTS: tuple[StoreArtifact, ...] = (
    StoreArtifact(
        "verdict journal", ("verdicts*.jsonl",), "journal",
        writers=("jepsen_tpu/store.py:VerdictJournal.record",),
        readers=("jepsen_tpu/store.py:VerdictJournal.load",),
        retention="store-lifetime",
        helpers=("shard_journal_path",),
        doc="resumable per-history verdict log (`verdicts-<k>.jsonl` "
            "per mesh shard); torn tail sealed on reopen, skipped on "
            "load; compaction is ROADMAP item 5"),
    StoreArtifact(
        "flight recorder", ("events.jsonl*",), "journal",
        writers=("jepsen_tpu/obs/events.py:emit",),
        readers=("jepsen_tpu/obs/events.py:load_events",),
        retention="rotated",
        doc="typed lifecycle events, one flushed line each; size-"
            "capped by `JEPSEN_TPU_EVENTS_MAX_BYTES` (atomic rename "
            "to `events.jsonl.1`, an `events_rotated` event opens "
            "the fresh log)"),
    StoreArtifact(
        "cost database", ("costdb*.jsonl",), "journal",
        writers=("jepsen_tpu/store.py:append_costdb",
                 "jepsen_tpu/mesh.py:merge_costdbs"),
        readers=("jepsen_tpu/store.py:load_costdb",),
        retention="merged",
        helpers=("costdb_path",),
        doc="per-(executable, geometry) device cost records; mesh "
            "shards append `costdb-shard<k>.jsonl`, the coordinator "
            "replaces the merged `costdb.jsonl` atomically"),
    StoreArtifact(
        "analytics ledger", ("analytics*.jsonl",), "journal",
        writers=("jepsen_tpu/store.py:append_analytics",
                 "jepsen_tpu/mesh.py:merge_analytics"),
        readers=("jepsen_tpu/store.py:load_analytics",),
        retention="merged",
        helpers=("analytics_path",),
        doc="kernel search telemetry (JEPSEN_TPU_KERNEL_STATS): one "
            "stats line per checked history (edge counts, closure "
            "rounds, SCC shape, decision-boundary margin); mesh "
            "shards append `analytics-shard<k>.jsonl`, the "
            "coordinator replaces the merged `analytics.jsonl` "
            "atomically"),
    StoreArtifact(
        # jt-lint: ok JT-TRACE-004 (the registry's declared pattern, not an ad-hoc spool writer)
        "worker trace spool", ("trace-*.jsonl",), "spool",
        writers=("jepsen_tpu/trace.py:ensure_worker_tracer",
                 "jepsen_tpu/trace.py:flush_worker_spool"),
        readers=("jepsen_tpu/trace.py:load_spool",),
        retention="per-sweep",
        helpers=("spool_path",),
        doc="per-pid span spool of one sweep's pool workers; stale "
            "spools cleared at sweep start, merged into trace.json "
            "at sweep end"),
    StoreArtifact(
        "shard spool dir", ("spool-shard*",), "spool",
        writers=("jepsen_tpu/trace.py:flush_worker_spool",),
        readers=("jepsen_tpu/trace.py:merge_shard_traces",),
        retention="per-sweep",
        helpers=("shard_spool_dir",),
        doc="one mesh shard's spool subdirectory (two hosts' workers "
            "can share a pid); removed by the coordinator after a "
            "fully-covered merge"),
    StoreArtifact(
        "health snapshot", ("health.json",), "snapshot",
        writers=("jepsen_tpu/obs/health.py:write_health",),
        readers=(),
        retention="replaced",
        doc="live progress/robustness/throughput snapshot, rewritten "
            "atomically every `JEPSEN_TPU_HEALTH_INTERVAL_S` seconds"),
    StoreArtifact(
        "sweep trace", ("trace.json", "trace-shard*.json"), "snapshot",
        writers=("jepsen_tpu/trace.py:Tracer.export",
                 "jepsen_tpu/trace.py:Tracer.export_merged",
                 "jepsen_tpu/trace.py:export_shard_trace",
                 "jepsen_tpu/mesh.py:_merge_trace_artifacts"),
        readers=("jepsen_tpu/trace.py:load_shard_trace",),
        retention="replaced",
        helpers=("shard_trace_path",),
        doc="merged Chrome trace of the sweep (per-shard exports "
            "under a mesh, folded by the coordinator)"),
    StoreArtifact(
        "metrics export", ("metrics.json", "metrics-shard*.json"),
        "snapshot",
        writers=("jepsen_tpu/trace.py:Tracer.export_metrics",
                 "jepsen_tpu/mesh.py:_merge_trace_artifacts"),
        readers=("jepsen_tpu/mesh.py:merge_shard_metrics",),
        retention="replaced",
        doc="the tracer's counters/gauges/histograms at sweep end"),
    StoreArtifact(
        "attribution report", ("report.json", "report.md"), "snapshot",
        writers=("jepsen_tpu/obs/attribution.py:write_report",),
        readers=(),
        retention="replaced",
        doc="critical-path attribution (`analyze-store --report`)"),
    StoreArtifact(
        "shard done marker", (".shard-*.done",), "marker",
        writers=("jepsen_tpu/supervisor.py:mark_shard_done",),
        readers=("jepsen_tpu/supervisor.py:load_shard_done",),
        retention="per-sweep",
        helpers=("shard_done_path",),
        doc="one mesh shard's completion marker (exit code + counts), "
            "cleared at its own sweep start, polled by the "
            "coordinator's bounded wait"),
    StoreArtifact(
        "latest/current links", ("latest", "current"), "marker",
        writers=("jepsen_tpu/store.py:Store._relink",),
        readers=(),
        retention="replaced",
        doc="monotonic symlinks to the newest run dir"),
    StoreArtifact(
        "serve tenant journal", ("serve-*.verdicts.jsonl",), "journal",
        writers=("jepsen_tpu/store.py:VerdictJournal.record",),
        readers=("jepsen_tpu/store.py:VerdictJournal.load",),
        retention="store-lifetime",
        helpers=("tenant_journal_path",),
        doc="one tenant's verdict log from the serve daemon — FULL "
            "result per line (journal-then-reply: written before the "
            "ack frame), replayed on reconnect without re-checking; "
            "compaction is ROADMAP item 5"),
    StoreArtifact(
        "serve request spool", ("serve-requests.jsonl",), "spool",
        writers=("jepsen_tpu/serve/daemon.py:RequestSpool.append",),
        readers=("jepsen_tpu/serve/daemon.py:RequestSpool.load",),
        retention="per-sweep",
        helpers=("request_spool_path",),
        doc="one flushed line per admitted request (tenant/id/"
            "checker) — crash triage for admitted-but-unverdicted "
            "work; cleared at daemon start"),
    StoreArtifact(
        "serve socket", ("serve.sock",), "marker",
        writers=("jepsen_tpu/serve/daemon.py:VerdictDaemon._bind",),
        readers=(),
        retention="per-sweep",
        helpers=("serve_socket_path",),
        doc="the daemon's unix listen socket "
            "(JEPSEN_TPU_SERVE_SOCKET overrides); a stale one (prior "
            "daemon SIGKILLed) is probe-reclaimed at bind, removed at "
            "drain"),
    StoreArtifact(
        "serve pidfile", ("serve.pid",), "marker",
        writers=("jepsen_tpu/serve/daemon.py:VerdictDaemon.start",),
        readers=(),
        retention="per-sweep",
        helpers=("serve_pid_path",),
        doc="the daemon's pid + listen address, published atomically "
            "(temp+`os.replace`), removed at drain"),
    StoreArtifact(
        "fleet member beacon", ("fleet-d*.json",), "snapshot",
        writers=("jepsen_tpu/serve/daemon.py:"
                 "VerdictDaemon._write_beacon",),
        readers=("jepsen_tpu/serve/fleet.py:"
                 "FleetRouter._wait_member_live",
                 "jepsen_tpu/serve/fleet.py:FleetRouter._scan"),
        retention="replaced",
        helpers=("fleet_member_path",),
        doc="one fleet daemon's heartbeat (pid/epoch/load), "
            "atomically replaced every JEPSEN_TPU_FLEET_HEARTBEAT_S; "
            "the router reads liveness off the kernel mtime (clock-"
            "skew immune) and load off the payload; retired at clean "
            "drain, left to go stale by a crash"),
    StoreArtifact(
        "fleet epoch marker", ("fleet-epoch.json",), "snapshot",
        writers=("jepsen_tpu/serve/fleet.py:FleetRouter._write_epoch",),
        readers=("jepsen_tpu/serve/daemon.py:VerdictDaemon._fenced",),
        retention="replaced",
        helpers=("fleet_epoch_path",),
        doc="the fleet membership epoch (atomic replace), bumped "
            "BEFORE any tenant reassignment — the fence a resurrected "
            "zombie daemon checks between a fold's compute and its "
            "journal writes, so it can never double-serve a "
            "reassigned tenant"),
    StoreArtifact(
        "fleet reassignment journal", ("fleet-reassign.jsonl",),
        "journal",
        writers=("jepsen_tpu/serve/fleet.py:"
                 "FleetRouter._append_reassign",),
        readers=("jepsen_tpu/serve/fleet.py:load_reassignments",),
        retention="per-sweep",
        helpers=("fleet_reassign_path",),
        doc="one line per failover move (epoch, dead member, tenant, "
            "successor, in-flight count) — the router's reassignment "
            "evidence for post-mortems; cleared at router start"),
    StoreArtifact(
        "fleet router socket", ("fleet.sock",), "marker",
        writers=("jepsen_tpu/serve/fleet.py:FleetRouter._bind",),
        readers=(),
        retention="per-sweep",
        helpers=("fleet_socket_path",),
        doc="the router's tenant-facing unix listen socket; a stale "
            "one is probe-reclaimed at bind, removed at stop"),
    StoreArtifact(
        "fleet daemon socket", ("fleet-d*.sock",), "marker",
        writers=("jepsen_tpu/serve/daemon.py:VerdictDaemon._bind",),
        readers=(),
        retention="per-sweep",
        helpers=("fleet_daemon_socket_path",),
        doc="fleet daemon <k>'s own listen socket (the router proxies "
            "tenant frames to it here); same probe-reclaim rule as "
            "serve.sock"),
    StoreArtifact(
        "dispatch plan", ("plan.json",), "snapshot",
        writers=("jepsen_tpu/planner.py:save_plan",),
        readers=("jepsen_tpu/planner.py:load_plan",),
        retention="replaced",
        helpers=("plan_path",),
        doc="the cost-aware planner's fitted model "
            "(JEPSEN_TPU_PLANNER): per-mode device-seconds "
            "coefficients fit from costdb × analytics, published "
            "temp+`os.replace` at sweep end; a corrupt or stale plan "
            "degrades to the deterministic heuristic fallback, never "
            "to a failed sweep"),
    StoreArtifact(
        "encoded sidecar", ("encoded*.bin",), "sidecar",
        writers=("jepsen_tpu/store.py:save_encoded",),
        readers=("jepsen_tpu/store.py:load_encoded",),
        retention="per-run",
        helpers=("encoded_cache_path",),
        doc="flat binary encode cache next to history.jsonl, keyed "
            "by the history's size/mtime/xxh64; written temp + "
            "`os.replace`, discarded on any key mismatch"),
    StoreArtifact(
        "AOT executable cache", ("*.jtx",), "snapshot",
        writers=("jepsen_tpu/aot.py:_disk_store",),
        readers=("jepsen_tpu/aot.py:_disk_load",),
        retention="replaced",
        root="cache",
        doc="serialized XLA executables under "
            "`~/.cache/jepsen_tpu/executables`; corrupt entries "
            "degrade to a fresh compile"),
    StoreArtifact(
        "jax profile capture", ("jax-profile",), "sidecar",
        writers=("jepsen_tpu/trace.py:jax_profile_session",),
        readers=(),
        retention="store-lifetime",
        doc="`jax.profiler` dump dir (JEPSEN_TPU_JAX_PROFILE)"),
)

#: Path-constructor helper name -> the artifact whose path it returns
#: (the fileflow pass's interprocedural edge: a call to one of these
#: resolves to the artifact wherever it appears).
PATH_HELPERS: dict[str, StoreArtifact] = {
    h: a for a in STORE_ARTIFACTS for h in a.helpers
}


def artifact_for_name(tail: str) -> StoreArtifact | None:
    """The declared artifact a file-name skeleton belongs to, or None
    (= an UNdeclared store write, JT-DUR-001). Skeletons carry `*` for
    interpolated segments; fnmatch treats the pattern's own `*` as the
    wildcard, so `costdb-shard*.jsonl` matches `costdb*.jsonl`."""
    for a in STORE_ARTIFACTS:
        for p in a.patterns:
            if fnmatchcase(tail, p):
                return a
    return None


#: README markers for the generated "Store durability" table — the
#: env-gate table's pattern: edit the registry, run `make dur-table`,
#: JT-DUR-006 fails the build on drift.
DUR_BEGIN = ("<!-- store-durability:begin "
             "(generated by jepsen_tpu.lint.contracts) -->")
DUR_END = "<!-- store-durability:end -->"


def _short(spec: str) -> str:
    """`store.py:VerdictJournal.record` for the table cell."""
    return spec.replace("jepsen_tpu/", "")


def render_dur_table() -> str:
    rows = ["| artifact | pattern | protocol | retention | "
            "writer → reader |", "|---|---|---|---|---|"]
    for a in STORE_ARTIFACTS:
        pats = " ".join(f"`{p}`" for p in a.patterns)
        w = ", ".join(_short(s) for s in a.writers) or "—"
        r = ", ".join(_short(s) for s in a.readers) or "—"
        rows.append(f"| {a.name} | {pats} | {a.protocol} | "
                    f"{a.retention or '—'} | {w} → {r} |")
    return "\n".join(rows)


def render_dur_block() -> str:
    return f"{DUR_BEGIN}\n{render_dur_table()}\n{DUR_END}"


# ---------------------------------------------------------------------------
# JT-ORD — happens-before contracts of the serve/fleet protocol
# ---------------------------------------------------------------------------

#: Marker syntax (matched per CFG pseudo-instruction, headers only for
#: compound statements):
#:
#:   ``call:<glob>``          a statement containing a call whose
#:                            loosely-dotted callee (subscript links
#:                            render as ``[]``: ``ent[].record``)
#:                            fnmatches the glob;
#:   ``call:<glob>{op=<v>}``  additionally requires a positional arg
#:                            that is a dict LITERAL with "op" == v
#:                            (frames built elsewhere stay unmatched
#:                            on purpose — the marker names a specific
#:                            emission, not a variable);
#:   ``set:<name>``           an assignment/augassign/annassign whose
#:                            target is the bare name or attribute
#:                            ``<name>``.
#:
#: Kinds — all proved path-sensitively on cfg.py graphs (finally
#: bodies routed, branch polarity recorded):
#:
#:   ``dominates``      first lies on EVERY entry→second path;
#:   ``postdominates``  second lies on EVERY first→exit path
#:                      (exception edges included);
#:   ``between``        mid lies on EVERY first→second path;
#:   ``never-after``    no path from first ever reaches second;
#:   ``under-lock``     first executes with ``lock`` MUST-held.
#:
#: ``guard`` names a bare local flag assigned exactly once: paths
#: taking the false arm of an ``if <guard>:`` are pruned, so a
#: release guarded by the same flag as its acquire is not a false
#: leak. Pruning is skipped (conservative) if the flag is ever
#: reassigned.

@dataclass(frozen=True)
class OrderContract:
    rule: str       #: JT-ORD rule id that proves this entry
    file: str       #: repo-relative module the contract lives in
    func: str       #: qualname within the module (iter_defs form)
    kind: str       #: dominates|postdominates|between|never-after|under-lock
    first: str      #: marker (see syntax above)
    second: str = ""
    mid: str = ""
    guard: str = ""
    lock: str = ""
    doc: str = ""


ORDER_CONTRACTS: tuple[OrderContract, ...] = (
    OrderContract(
        rule="JT-ORD-001",
        file="jepsen_tpu/serve/daemon.py",
        func="VerdictDaemon._run_fold",
        kind="dominates",
        first="call:*.record",
        second="call:*.send",
        doc="journal-then-reply: the journal append dominates every "
            "reply-frame send, so an ack can only name a verdict the "
            "journal already holds (or explicitly flags journaled: "
            "false)"),
    OrderContract(
        rule="JT-ORD-002",
        file="jepsen_tpu/serve/daemon.py",
        func="VerdictDaemon._run_fold",
        kind="between",
        first="call:*.verdicts",
        mid="call:*._fenced",
        second="call:*.record",
        doc="the zombie fence: the epoch-fence read lies on every "
            "path between a fold's dispatch and its journal write"),
    OrderContract(
        rule="JT-ORD-002",
        file="jepsen_tpu/serve/daemon.py",
        func="VerdictDaemon._run_fold",
        kind="never-after",
        first="call:*.request_drain",
        second="call:*.record",
        doc="a fenced fold drains and drops: once the fold entered "
            "the fenced path no journal write may follow — the "
            "successor is already journaling these ids"),
    OrderContract(
        rule="JT-ORD-003",
        file="jepsen_tpu/serve/fleet.py",
        func="FleetRouter._fail_over",
        kind="dominates",
        first="call:*._write_epoch",
        second="call:os.kill",
        doc="fence before STONITH: the epoch bump is durably "
            "published (temp+os.replace) before the dead member's "
            "process is signalled"),
    OrderContract(
        rule="JT-ORD-003",
        file="jepsen_tpu/serve/fleet.py",
        func="FleetRouter._fail_over",
        kind="dominates",
        first="call:*._write_epoch",
        second="call:*.send{op=adopt}",
        doc="fence before adoption: a successor only learns it owns "
            "a tenant after the epoch fence that stops the old "
            "owner is on disk"),
    OrderContract(
        rule="JT-ORD-003",
        file="jepsen_tpu/serve/fleet.py",
        func="FleetRouter._fail_over",
        kind="never-after",
        first="call:*.send{op=adopt}",
        second="call:os.kill",
        doc="STONITH precedes adoption and never follows it: "
            "signalling the old owner after a successor adopted "
            "would be fencing out of order"),
    OrderContract(
        rule="JT-ORD-004",
        file="jepsen_tpu/parallel/__init__.py",
        func="_sync_check",
        kind="postdominates",
        first="call:_note_donation",
        second="call:*.release",
        guard="donate",
        doc="no leaked device slot: the DeviceSlots release "
            "post-dominates the donation acquire on every exit path, "
            "exception edges included"),
    OrderContract(
        rule="JT-ORD-005",
        file="jepsen_tpu/serve/scheduler.py",
        func="Admission.close",
        kind="under-lock",
        first="set:_closed",
        lock="self._cv",
        doc="admission close happens under its condition variable: "
            "a waiter never misses the wakeup that tells it the "
            "queue closed"),
    OrderContract(
        rule="JT-ORD-005",
        file="jepsen_tpu/serve/daemon.py",
        func="VerdictDaemon.request_drain",
        kind="dominates",
        first="call:*.close",
        second="call:*._draining.set",
        doc="close-before-drain-visible: admission is closed before "
            "the draining flag becomes observable, so the scheduler "
            "can never see draining ∧ pending==0 while a reader can "
            "still admit a request nobody will serve"),
)
