"""The declared-contracts registry the cross-boundary analyses check
against.

`gates.py` proved the pattern: an invariant written down ONCE, in a
typed table, is an invariant the linter can enforce everywhere it is
consumed. This module does the same for the encode→pack→dispatch
tensor contracts (JT-TENSOR), the lock/shared-state discipline of the
sweep's thread graph (JT-LOCK), and the hot-path scoping both share.
The ABI/layout contracts (JT-ABI) are NOT declared here — their source
of truth is `native/hist_encode.cc` itself, parsed by `cparse.py` and
cross-checked against `native_lib.py`/`store.py`; duplicating them in
a third place would just add one more thing to drift.

Every table is consumed by a rule in `rules_tensor.py` /
`rules_lock.py`; tests/test_lint.py pins the registry's shape so an
entry can't silently vanish.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# JT-TENSOR — dtype/shape/fill contracts of the encode→pack→dispatch path
# ---------------------------------------------------------------------------

#: Canonical dtype per encoded-tensor field (numpy dtype names). The
#: lean int64 index tensors and the int32 device (`d_*`) tensors are
#: both declared — the narrowing between them is explicit below, so an
#: UNdeclared cast anywhere on the path is a finding.
TENSOR_DTYPES: dict[str, str] = {
    "appends": "int32",
    "reads": "int32",
    "edges": "int32",
    "status": "int32",
    "process": "int32",
    "invoke_index": "int64",
    "complete_index": "int64",
    "d_invoke": "int32",
    "d_complete": "int32",
    "n_txns": "int32",
    "kid_to_pre": "int32",
}

#: Common local-variable spellings of the declared fields (the packers
#: shorten `*_index` to `*_idx`); dataflow tags resolve through this.
FIELD_ALIASES: dict[str, str] = {
    "invoke_idx": "invoke_index",
    "complete_idx": "complete_index",
}

#: Sanctioned narrowings: (source field, destination dtype). The v2
#: sidecar's device tensors are the int32 narrowing of the lean int64
#: index tensors — declared here because both writers (store.py's
#: `_padded_arrays`, hist_encode.cc's `write_sidecar`) perform it; any
#: OTHER cast of a contracted tensor is drift.
DECLARED_NARROWINGS: frozenset[tuple[str, str]] = frozenset({
    ("invoke_index", "int32"),
    ("complete_index", "int32"),
})

#: pack_batch's fill convention: dead triple/process rows are -1 (no
#: txn, no key), dead index rows 0. A `np.full` building a contracted
#: tensor with any other fill silently corrupts the kernel's masking.
FILL_VALUES: dict[str, int] = {
    "appends": -1,
    "reads": -1,
    "edges": -1,
    "process": -1,
    "invoke_index": 0,
    "complete_index": 0,
    "d_invoke": 0,
    "d_complete": 0,
    "n_txns": 0,
}

#: Fields whose minor axis is a triple — a reshape of one of these to
#: a literal shape must end in 3.
TRIPLE_FIELDS: frozenset[str] = frozenset({"appends", "reads", "edges"})

#: The bucket geometry: txn axis pads to the MXU tile, every minor
#: axis to 8 — kernels.BatchShape.plan, store.dispatch_pad_plan and
#: hist_encode.cc's pad_up all agree on these two numbers (JT-ABI-004
#: proves the native side; JT-TENSOR-003 flags any literal pad
#: multiple outside this set on the Python side).
PAD_TXNS = 128
PAD_MINOR = 8
PAD_MULTIPLES: frozenset[int] = frozenset({PAD_TXNS, PAD_MINOR})

#: Donated-arg positions of a single-device bucket dispatch: the six
#: packed input tensors, nothing else. `donate_argnums` anywhere in
#: the analyzed files must spell exactly this.
DONATE_ARGNUMS: tuple[int, ...] = (0, 1, 2, 3, 4, 5)

#: Files whose whole body is the pack/h2d hot path for the host-
#: materialization rule (JT-TENSOR-002, ex-JT-JAX-005).
HOT_PATH_FILES = ("jepsen_tpu/parallel/", "jepsen_tpu/shm.py")

#: Function-name shapes treated as hot-path regardless of file — the
#: packers and h2d stages (also what makes the rule fixture-testable).
HOT_FN_PREFIXES = ("pack_", "_h2d", "_prep_bucket", "shard_batch")

#: Files the tensor dataflow pass analyzes module-wide (beyond the
#: hot-path scoping above): everywhere contracted tensors are built,
#: persisted, or packed.
TENSOR_FILES = (
    "jepsen_tpu/checker/elle/kernels.py",
    "jepsen_tpu/checker/knossos/kernels.py",
    "jepsen_tpu/parallel/",
    "jepsen_tpu/shm.py",
    "jepsen_tpu/store.py",
)


def is_tensor_file(rel: str) -> bool:
    return any(t in rel for t in TENSOR_FILES)


def is_hot_path_file(rel: str) -> bool:
    return any(h in rel for h in HOT_PATH_FILES)


def field_of(name: str) -> str | None:
    """The declared field a local name refers to, or None."""
    name = FIELD_ALIASES.get(name, name)
    return name if name in TENSOR_DTYPES else None


# ---------------------------------------------------------------------------
# JT-LOCK — shared state, its guarding locks, and blocking calls
# ---------------------------------------------------------------------------

#: Shared mutable state and the lock that must be held to WRITE it:
#: (class name, attribute, lock). The lock is either a `self.<attr>`
#: spelled as the attr name, or a module-global lock name. Reads are
#: out of scope (the registry entries are all either monotonic
#: counters or snapshot-read-by-design); `__init__` is exempt
#: (construction is single-threaded by definition). These are exactly
#: the structures the PR-6/7 review passes found raced by hand: the
#: donated-slot ledger, the health snapshot's seq, the tracer's
#: metric cells.
SHARED_STATE: tuple[tuple[str, str, str], ...] = (
    ("DeviceSlotLedger", "_inflight", "_lock"),
    ("HealthSampler", "_seq", "_wlock"),
    ("Counter", "value", "_MLOCK"),
    ("Histogram", "count", "_MLOCK"),
    ("Histogram", "total", "_MLOCK"),
    ("Histogram", "min", "_MLOCK"),
    ("Histogram", "max", "_MLOCK"),
    ("_Injector", "_fired", "_lock"),
)

#: Calls that park the calling thread for unbounded/long time — doing
#: one while holding a lock starves every other waiter (the "gauge
#: published outside the lock" / "write_snapshot serialized" class,
#: inverted). Consumed by rules_lock._is_blocking in three forms:
#: exact dotted names, dotted-name prefixes, and attribute-call tails.
#: `.join()` is deliberately NOT here: the spelling is shared with
#: `str.join` (every f-string-averse formatter in the tree), and a
#: receiver-type analysis precise enough to split them doesn't fit a
#: lexical pass — thread joins under a lock surface via JT-LOCK-001's
#: call-graph edges instead when the joined worker takes locks.
BLOCKING_EXACT: frozenset[str] = frozenset({"time.sleep", "sleep"})
BLOCKING_PREFIXES: tuple[str, ...] = ("subprocess.",)
BLOCKING_METHOD_TAILS: frozenset[str] = frozenset({
    "block_until_ready",   # unbounded device wait
    "result",              # Future.result
})

#: Constructors whose instances are thread-safe by design: a Thread
#: target may share these with its spawner freely (JT-LOCK-004's
#: confinement rule skips them).
THREADSAFE_CTORS: frozenset[str] = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "Semaphore", "BoundedSemaphore", "Event", "Lock", "RLock",
    "Condition", "Barrier", "deque",
})
