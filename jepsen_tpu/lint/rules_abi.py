"""JT-ABI — the ABI/layout prover across the C++/Python boundary.

The native ABI churned v3→v4→v5 across three PRs, and every bump
touched four places that nothing machine-checked against each other:
the `extern "C"` exports in `native/*.cc`, the ctypes prototypes in
`native_lib.py`, the version constant both sides pin, and the
`encoded.v1/v2.bin` layout mirrored between `hist_encode.cc`'s
`write_sidecar` and `store.py`. A half-landed bump — a new export
with no prototype, an argtype that silently truncates, a pad constant
changed on one side — either crashes at dlopen (the good case) or
corrupts tensors at a distance (the case this family exists for).

Four project rules, all driven by `cparse.parse_native` on the C side
and plain `ast` extraction on the Python side:

  JT-ABI-001  export/prototype coverage drift (symbol sets differ)
  JT-ABI-002  ABI version constant drift (C return vs Python check)
  JT-ABI-003  prototype drift (arity / incompatible ctypes per arg)
  JT-ABI-004  sidecar layout drift (pad geometry, hash span, xxh64
              primes, magic strings, field write order)

Everything is path-relative to the ProjectCtx root, so the
seeded-mutation harness (tests/test_contract_prover.py) can point the
rules at a fixture tree whose .cc / native_lib.py / store.py copies
carry exactly one induced drift each.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path
from typing import Iterator

from . import Finding, ProjectCtx, ProjectRule, const_str, dotted
from . import cparse, dataflow

_NATIVE_SOURCES = ("native/hist_encode.cc", "native/wgl.cc",
                   "native/graph_algo.cc")
_NATIVE_LIB = "jepsen_tpu/native_lib.py"
_STORE = "jepsen_tpu/store.py"
_ENCODE = "jepsen_tpu/checker/elle/encode.py"

#: Normalized C type → ctypes renders that faithfully bind it.
CTYPES_COMPAT: dict[str, frozenset[str]] = {
    "void": frozenset({"None"}),
    "int32_t": frozenset({"c_int32"}),
    "int64_t": frozenset({"c_int64"}),
    "uint32_t": frozenset({"c_uint32"}),
    "uint64_t": frozenset({"c_uint64"}),
    "double": frozenset({"c_double"}),
    "float": frozenset({"c_float"}),
    "char*": frozenset({"c_char_p"}),
    "void*": frozenset({"c_void_p"}),
    "uint8_t*": frozenset({"c_char_p", "POINTER(c_uint8)"}),
    "int32_t*": frozenset({"POINTER(c_int32)"}),
    "int64_t*": frozenset({"POINTER(c_int64)"}),
    "uint64_t*": frozenset({"POINTER(c_uint64)"}),
}


@dataclass
class Proto:
    """One ctypes prototype bound in native_lib.py."""

    name: str
    restype: str | None
    argtypes: tuple[str, ...] | None
    line: int


# ---------------------------------------------------------------------------
# Python-side extraction
# ---------------------------------------------------------------------------

def _render_ctype(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """'c_int64' / 'POINTER(c_int32)' / 'None' for a ctypes type
    expression; None when unrenderable (dynamic)."""
    if isinstance(node, ast.Constant) and node.value is None:
        return "None"
    if isinstance(node, ast.Name):
        return aliases.get(node.id, node.id)
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        if d and d.split(".")[-1] == "POINTER" and node.args:
            inner = _render_ctype(node.args[0], aliases)
            return f"POINTER({inner})" if inner else None
    return None


def extract_ctypes(tree: ast.Module) -> tuple[dict[str, Proto],
                                              dict[str, tuple[int, int]]]:
    """(prototypes, version checks) from native_lib.py's AST.

    Prototypes come from `L.jt_x.restype/argtypes = ...` assignments,
    including the `for name in ("jt_a", "jt_b"): fn = getattr(L, name)`
    batch form. Version checks are `if L.jt_x_abi_version() != N`
    comparisons, mapped name → (N, line)."""
    protos: dict[str, Proto] = {}
    checks: dict[str, tuple[int, int]] = {}

    def proto(name: str, line: int) -> Proto:
        p = protos.get(name)
        if p is None:
            p = protos[name] = Proto(name, None, None, line)
        return p

    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        aliases: dict[str, str] = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                r = _render_ctype(n.value, aliases)
                if r is not None and ("POINTER" in r
                                      or r.startswith("c_")):
                    aliases[n.targets[0].id] = r

        def record(name: str, attr: str, value: ast.AST,
                   line: int) -> None:
            p = proto(name, line)
            if attr == "restype":
                p.restype = _render_ctype(value, aliases)
            elif attr == "argtypes":
                if isinstance(value, (ast.List, ast.Tuple)):
                    rendered = tuple(
                        _render_ctype(e, aliases) or "?"
                        for e in value.elts)
                    p.argtypes = rendered

        for n in ast.walk(fn):
            # L.jt_x.restype = ... / L.jt_x.argtypes = [...]
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Attribute):
                t = n.targets[0]
                if t.attr in ("restype", "argtypes") \
                        and isinstance(t.value, ast.Attribute) \
                        and t.value.attr.startswith("jt_"):
                    record(t.value.attr, t.attr, n.value, n.lineno)
            # for name in ("jt_a", ...): fn = getattr(L, name); fn.restype = ...
            elif isinstance(n, ast.For) \
                    and isinstance(n.iter, (ast.Tuple, ast.List)):
                names = [const_str(e) for e in n.iter.elts]
                if not names or not all(
                        s and s.startswith("jt_") for s in names):
                    continue
                bound: set[str] = set()
                for b in ast.walk(n):
                    if isinstance(b, ast.Assign) \
                            and isinstance(b.value, ast.Call) \
                            and dotted(b.value.func) == "getattr" \
                            and isinstance(b.targets[0], ast.Name):
                        bound.add(b.targets[0].id)
                for b in ast.walk(n):
                    if isinstance(b, ast.Assign) \
                            and isinstance(b.targets[0], ast.Attribute):
                        t = b.targets[0]
                        if t.attr in ("restype", "argtypes") \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in bound:
                            for s in names:
                                record(s, t.attr, b.value, b.lineno)
            # if L.jt_x_abi_version() != N: ...
            elif isinstance(n, ast.Compare) and len(n.ops) == 1 \
                    and isinstance(n.ops[0], (ast.NotEq, ast.Eq)) \
                    and isinstance(n.left, ast.Call):
                d = dotted(n.left.func)
                tail = d.split(".")[-1] if d else ""
                c = n.comparators[0]
                if tail.startswith("jt_") \
                        and tail.endswith("abi_version") \
                        and isinstance(c, ast.Constant) \
                        and isinstance(c.value, int):
                    checks[tail] = (c.value, n.lineno)
    return protos, checks


# ---------------------------------------------------------------------------
# store.py / encode.py layout extraction
# ---------------------------------------------------------------------------

@dataclass
class StoreLayout:
    consts: dict[str, tuple[int, int]]          # name -> (value, line)
    magics: dict[str, tuple[bytes, int]]        # name -> (value, line)
    field_orders: dict[str, tuple[tuple[str, ...], int]]


def _int_of(node: ast.AST, consts: dict[str, int]) -> int | None:
    v = dataflow.int_value(node, consts)
    if v is not None:
        return v
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Pow):
        b = dataflow.int_value(node.left, consts)
        e = dataflow.int_value(node.right, consts)
        if b is not None and e is not None and 0 <= e < 128:
            return b ** e
    if isinstance(node, ast.Call) and len(node.args) == 1:
        # np.int64(2**30)-style wrap
        return _int_of(node.args[0], consts)
    return None


def extract_store_layout(tree: ast.Module) -> StoreLayout:
    lay = StoreLayout({}, {}, {})
    known: dict[str, int] = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            name = n.targets[0].id
            if isinstance(n.value, ast.Constant) \
                    and isinstance(n.value.value, bytes):
                lay.magics[name] = (n.value.value, n.lineno)
                continue
            v = _int_of(n.value, known)
            if v is not None:
                known[name] = v
                lay.consts[name] = (v, n.lineno)
                continue
            if isinstance(n.value, ast.Dict):
                fields: dict[str, tuple[str, ...]] = {}
                for k, val in zip(n.value.keys, n.value.values):
                    ks = const_str(k) if k is not None else None
                    if ks and isinstance(val, (ast.Tuple, ast.List)) \
                            and all(const_str(e) for e in val.elts):
                        fields[ks] = tuple(const_str(e)
                                           for e in val.elts)
                for ks, fs in fields.items():
                    lay.field_orders[f"ENCODED_FIELDS[{ks!r}]"] = \
                        (fs, n.lineno)
    for fn in ast.walk(tree):
        if isinstance(fn, ast.FunctionDef) \
                and fn.name == "_padded_arrays":
            for r in ast.walk(fn):
                if isinstance(r, ast.Return) \
                        and isinstance(r.value, ast.List):
                    names = []
                    for e in r.value.elts:
                        if isinstance(e, ast.Tuple) and e.elts \
                                and const_str(e.elts[0]):
                            names.append(const_str(e.elts[0]))
                    if names:
                        lay.field_orders["_padded_arrays"] = \
                            (tuple(names), r.lineno)
    return lay


def _is_subsequence(sub: tuple[str, ...],
                    full: tuple[str, ...]) -> bool:
    it = iter(full)
    return all(s in it for s in sub)


# ---------------------------------------------------------------------------
# The shared project-context cache and the four rules
# ---------------------------------------------------------------------------

def _tree(ctx: ProjectCtx, rel: str) -> ast.Module | None:
    """One Python input of the prover, through the run's SHARED parse
    (ProjectCtx.module — the module-rule pass already parsed these
    files). None when missing or unparseable: the prover must
    DEGRADE on a broken file, never crash the run — the module pass
    already reports the syntax error as a JT-PARSE finding, and a
    half-parsed ABI would only add false drift on top of it."""
    m = ctx.module(rel)
    return None if m is None else m.tree


class _AbiState:
    def __init__(self, ctx: ProjectCtx):
        root = Path(ctx.root)
        self.native: dict[str, cparse.NativeABI] = {}
        for rel in _NATIVE_SOURCES:
            p = root / rel
            if p.is_file():
                try:
                    self.native[rel] = cparse.parse_native(
                        p.read_text(encoding="utf-8",
                                    errors="replace"), rel)
                except OSError:
                    pass
        self.protos: dict[str, Proto] = {}
        self.checks: dict[str, tuple[int, int]] = {}
        lib_tree = _tree(ctx, _NATIVE_LIB)
        self.lib_present = lib_tree is not None
        if lib_tree is not None:
            self.protos, self.checks = extract_ctypes(lib_tree)
        store_tree = _tree(ctx, _STORE)
        self.store: StoreLayout | None = \
            extract_store_layout(store_tree) \
            if store_tree is not None else None
        self.never_completed: int | None = None
        etree = _tree(ctx, _ENCODE)
        if etree is not None:
            for n in etree.body:
                if isinstance(n, ast.Assign) \
                        and isinstance(n.targets[0], ast.Name) \
                        and n.targets[0].id == "NEVER_COMPLETED":
                    self.never_completed = _int_of(n.value, {})

    def exports(self) -> dict[str, tuple[cparse.CSig, str]]:
        out = {}
        for rel, abi in self.native.items():
            for name, sig in abi.exports.items():
                out[name] = (sig, rel)
        return out


def _state(ctx: ProjectCtx) -> _AbiState:
    st = getattr(ctx, "_abi_state", None)
    if st is None:
        st = _AbiState(ctx)
        ctx._abi_state = st
    return st


class ExportCoverageDrift(ProjectRule):
    id = "JT-ABI-001"
    doc = ("an exported `jt_*` symbol with no ctypes prototype in "
           "native_lib.py, or a prototype for a symbol no .cc "
           "exports — a half-landed ABI change")
    hint = ("bind the new export in the matching _bind_* (restype + "
            "argtypes), or delete the orphaned prototype")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        st = _state(ctx)
        if not st.native or not st.lib_present:
            return
        exports = st.exports()
        bound = set(st.protos) | set(st.checks)
        for name, (sig, rel) in sorted(exports.items()):
            if name not in bound:
                yield Finding(self.id, _NATIVE_LIB, 1,
                              f"export `{name}` ({rel}:{sig.line}) "
                              "has no ctypes prototype", self.hint)
        for name, p in sorted(st.protos.items()):
            if name not in exports:
                yield Finding(self.id, _NATIVE_LIB, p.line,
                              f"ctypes prototype for `{name}` but no "
                              "native export", self.hint)


class AbiVersionDrift(ProjectRule):
    id = "JT-ABI-002"
    doc = ("the ABI version a `jt_*_abi_version()` export returns "
           "differs from (or is never checked against) the literal "
           "native_lib.py compares at bind time")
    hint = ("bump BOTH sides together: the C return and the "
            "`!= N` guard in the matching _bind_*")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        st = _state(ctx)
        if not st.native or not st.lib_present:
            return
        for rel, abi in sorted(st.native.items()):
            for name, cval in sorted(abi.abi_versions.items()):
                chk = st.checks.get(name)
                if chk is None:
                    yield Finding(
                        self.id, _NATIVE_LIB, 1,
                        f"`{name}` ({rel}) returns {cval} but "
                        "native_lib.py never checks it — a stale .so "
                        "would bind silently", self.hint)
                elif chk[0] != cval:
                    yield Finding(
                        self.id, _NATIVE_LIB, chk[1],
                        f"ABI version drift for `{name}`: C++ returns "
                        f"{cval}, native_lib checks {chk[0]}",
                        self.hint)


class PrototypeDrift(ProjectRule):
    id = "JT-ABI-003"
    doc = ("a ctypes prototype whose arity or types no longer match "
           "the C signature — calls through it corrupt arguments "
           "instead of failing")
    hint = ("update restype/argtypes to mirror the C signature "
            "(see rules_abi.CTYPES_COMPAT for the faithful binding)")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        st = _state(ctx)
        if not st.native or not st.lib_present:
            return
        exports = st.exports()
        for name, p in sorted(st.protos.items()):
            if name not in exports:
                continue     # JT-ABI-001's finding
            sig, rel = exports[name]
            where = f"{rel}:{sig.line}"
            if p.argtypes is not None:
                if len(p.argtypes) != len(sig.args):
                    yield Finding(
                        self.id, _NATIVE_LIB, p.line,
                        f"`{name}` takes {len(sig.args)} args in C "
                        f"({where}) but argtypes declares "
                        f"{len(p.argtypes)}", self.hint)
                else:
                    for i, (c, py) in enumerate(zip(sig.args,
                                                    p.argtypes)):
                        ok = CTYPES_COMPAT.get(c)
                        if ok is not None and py not in ok:
                            yield Finding(
                                self.id, _NATIVE_LIB, p.line,
                                f"`{name}` arg {i} is `{c}` in C "
                                f"({where}) but bound as `{py}`",
                                self.hint)
            if p.restype is not None:
                ok = CTYPES_COMPAT.get(sig.ret)
                if ok is not None and p.restype not in ok:
                    yield Finding(
                        self.id, _NATIVE_LIB, p.line,
                        f"`{name}` returns `{sig.ret}` in C ({where}) "
                        f"but restype is `{p.restype}`", self.hint)


#: (C constant in hist_encode.cc, Python constant in store.py)
_CONST_PAIRS = (
    ("PAD_TXNS", "_PAD_TXNS"), ("PAD_MINOR", "_PAD_MINOR"),
    ("HASH_SPAN", "_HASH_SPAN"),
    ("XP1", "_X1"), ("XP2", "_X2"), ("XP3", "_X3"),
    ("XP4", "_X4"), ("XP5", "_X5"),
)

_HIST = "native/hist_encode.cc"


class SidecarLayoutDrift(ProjectRule):
    id = "JT-ABI-004"
    doc = ("encoded.v1/v2.bin layout drift between hist_encode.cc and "
           "store.py: pad geometry, hash span, xxh64 primes, magic "
           "strings, or the field write order")
    hint = ("the sidecar layout is defined in BOTH writers — change "
            "them together (store.save_encoded/_padded_arrays and "
            "hist_encode.cc write_sidecar)")

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        st = _state(ctx)
        abi = st.native.get(_HIST)
        if abi is None or st.store is None:
            return
        lay = st.store
        for cname, pyname in _CONST_PAIRS:
            cv = abi.constants.get(cname)
            pv = lay.consts.get(pyname)
            if cv is not None and pv is not None and cv != pv[0]:
                yield Finding(
                    self.id, _STORE, pv[1],
                    f"layout constant drift: {pyname}={pv[0]} but "
                    f"{_HIST} {cname}={cv}", self.hint)
        sc = abi.constants.get("SC_NEVER")
        if sc is not None and st.never_completed is not None \
                and sc != st.never_completed:
            yield Finding(
                self.id, _ENCODE, 1,
                f"NEVER_COMPLETED={st.never_completed} but {_HIST} "
                f"SC_NEVER={sc} — effective completion keys diverge "
                "between the writers", self.hint)
        if abi.magics:
            for name in ("ENCODED_MAGIC", "ENCODED_MAGIC_V2"):
                m = lay.magics.get(name)
                if m is not None and m[0] not in abi.magics:
                    yield Finding(
                        self.id, _STORE, m[1],
                        f"{name}={m[0]!r} is not a magic the native "
                        f"writer can produce ({sorted(abi.magics)})",
                        self.hint)
        if abi.sidecar_fields:
            for label, (fields, line) in sorted(
                    lay.field_orders.items()):
                if not _is_subsequence(fields, abi.sidecar_fields):
                    yield Finding(
                        self.id, _STORE, line,
                        f"sidecar field order drift: {label} = "
                        f"{fields} is not written in this order by "
                        f"{_HIST} write_sidecar "
                        f"({abi.sidecar_fields})", self.hint)


RULES = [ExportCoverageDrift(), AbiVersionDrift(), PrototypeDrift(),
         SidecarLayoutDrift()]
