"""JT-JAX — host-sync / recompile hazards in jitted code.

The paper's verdict-parity guarantee (TPU verdicts identical to the
Elle/Knossos CPU checkers) dies silently the moment a host sync or a
shape-driven recompile slips into a jitted path: `.item()` and
`np.asarray` on a traced value force a device→host transfer per call,
and a Python `if` on a tracer either crashes (ConcretizationError) or
— worse — got hoisted to trace time and bakes one branch into the
compiled kernel. These rules police the hazards lexically: inside
`@jax.jit`-decorated functions everywhere, plus module-wide in the
kernel modules (`checker/elle/kernels.py`, `checker/elle/
pallas_square.py`, `checker/knossos/`), and `block_until_ready`
anywhere outside the sanctioned watchdog wrappers (`parallel/`,
`supervisor.py`).
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, const_str, dotted

#: Modules whose ENTIRE body is treated as kernel code for JT-JAX-001.
_KERNEL_MODULES = ("jepsen_tpu/checker/elle/kernels.py",
                   "jepsen_tpu/checker/elle/pallas_square.py")
_KERNEL_PREFIXES = ("jepsen_tpu/checker/knossos/",)

#: Modules sanctioned to call block_until_ready (the watchdog wrappers).
_BUR_ALLOWED = ("jepsen_tpu/parallel/", "jepsen_tpu/supervisor.py")

_NP_NAMES = {"np", "numpy", "onp"}
_NP_MATERIALIZERS = {"array", "asarray", "ascontiguousarray",
                     "frombuffer", "copy"}


def _in_kernel_module(rel: str) -> bool:
    return rel.endswith(_KERNEL_MODULES) \
        or any(p in rel for p in _KERNEL_PREFIXES)


def _is_jit_decorator(dec: ast.AST) -> bool:
    d = dotted(dec)
    if d and (d == "jit" or d.endswith(".jit")):
        return True
    if isinstance(dec, ast.Call):
        # functools.partial(jax.jit, static_argnames=...)
        cd = dotted(dec.func)
        if cd and (cd == "partial" or cd.endswith(".partial")):
            if dec.args:
                ad = dotted(dec.args[0])
                return ad is not None and (ad == "jit"
                                           or ad.endswith(".jit"))
        # jax.jit(..., static_argnames=...) used as a decorator factory
        return cd is not None and (cd == "jit" or cd.endswith(".jit"))
    return False


def _static_names(fn: ast.FunctionDef, dec: ast.AST) -> set[str]:
    """Parameter names declared static on the jit decorator — branching
    on those is legitimate (it recompiles, by design)."""
    out: set[str] = set()
    if not isinstance(dec, ast.Call):
        return out
    argnames = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                s = const_str(e)
                if s:
                    out.add(s)
        elif kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) \
                        and isinstance(e.value, int) \
                        and 0 <= e.value < len(argnames):
                    out.add(argnames[e.value])
    return out


def _jit_functions(tree: ast.AST) -> Iterator[tuple[ast.FunctionDef,
                                                    set[str]]]:
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if _is_jit_decorator(dec):
                yield node, _static_names(node, dec)
                break


def _jits(ctx) -> list:
    """The module's jitted functions, memoized on the ModuleCtx —
    all three JT-JAX rules share one decorator walk per file."""
    cached = getattr(ctx, "_jax_jits", None)
    if cached is None:
        cached = list(_jit_functions(ctx.tree))
        ctx._jax_jits = cached
    return cached


def _traced_params(fn: ast.FunctionDef, static: set[str]) -> set[str]:
    a = fn.args
    names = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    return names - static - {"self", "cls"}


class ItemHostSync(ModuleRule):
    id = "JT-JAX-001"
    doc = (".item() in a jitted function (or anywhere in a kernel "
           "module) — a per-call device->host sync")
    hint = ("keep the value on device (jnp ops / lax.cond), or move "
            "the readback outside the jitted path")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        def items(tree) -> Iterator[ast.Call]:
            for n in ast.walk(tree):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "item" and not n.args:
                    yield n

        if _in_kernel_module(ctx.rel):
            for n in items(ctx.tree):
                yield self.finding(ctx, n,
                                   ".item() host-sync in a kernel module")
            return
        for fn, _static in _jits(ctx):
            for n in items(fn):
                yield self.finding(
                    ctx, n, f".item() inside jitted `{fn.name}`")


class NumpyOnTraced(ModuleRule):
    id = "JT-JAX-002"
    doc = ("np.array/np.asarray (and friends) inside a jitted "
           "function — materializes the tracer on host, forcing a "
           "sync or a ConcretizationError")
    hint = "use jnp.* inside jit; np belongs outside the traced region"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for fn, _static in _jits(ctx):
            for n in ast.walk(fn):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _NP_MATERIALIZERS \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id in _NP_NAMES:
                    yield self.finding(
                        ctx, n,
                        f"np.{n.func.attr}() inside jitted `{fn.name}`")


class BlockUntilReadyOutsideWatchdog(ModuleRule):
    id = "JT-JAX-003"
    doc = ("block_until_ready outside the sanctioned watchdog "
           "wrappers (parallel/, supervisor.py) — an unbounded, "
           "unattributed device wait")
    hint = ("route the wait through parallel's bounded/attributed "
            "wrappers (watchdog + device-window tracing)")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if any(a in ctx.rel for a in _BUR_ALLOWED):
            return
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if (isinstance(n.func, ast.Attribute)
                        and n.func.attr == "block_until_ready") \
                        or (d and d.endswith("block_until_ready")):
                    yield self.finding(ctx, n,
                                       "unsanctioned block_until_ready")


# JT-JAX-005 (host copy on the pack/h2d hot path) was SUBSUMED by
# JT-TENSOR-002 in rules_tensor.py, which runs the same hot-path
# scoping through the tensor dataflow pass (and additionally catches
# np.array of a contracted tensor and .tolist() materializations).
# The id is retired, not renumbered — see MIGRATING.md.


class TracerBranch(ModuleRule):
    id = "JT-JAX-004"
    doc = ("Python if/ternary on a traced parameter inside a jitted "
           "function — ConcretizationError at best, a silently "
           "trace-time-frozen branch at worst")
    hint = ("use lax.cond/jnp.where, or declare the argument in "
            "static_argnames if recompiling per value is intended")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for fn, static in _jits(ctx):
            traced = _traced_params(fn, static)
            if not traced:
                continue
            for n in ast.walk(fn):
                if isinstance(n, (ast.If, ast.IfExp)):
                    used = {x.id for x in ast.walk(n.test)
                            if isinstance(x, ast.Name)}
                    hit = sorted(used & traced)
                    if hit:
                        yield self.finding(
                            ctx, n,
                            f"Python branch on traced {', '.join(hit)} "
                            f"inside jitted `{fn.name}`")


RULES = [ItemHostSync(), NumpyOnTraced(),
         BlockUntilReadyOutsideWatchdog(), TracerBranch()]
