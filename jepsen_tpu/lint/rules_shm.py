"""JT-SHM — shared-memory lifecycle.

The zero-copy ingest transport's leak discipline (PR 3): every
`SharedMemory(create=True)` must be lexically paired with an unlink
path in the same function — the happy path unlinks on materialize, the
failure path sweeps via `unlink_stale` — because a created-but-never-
unlinked segment survives the process and fills /dev/shm until the
host starts failing allocations. The check is a dataflow-lite pass
over the enclosing function: a create with no reachable
`.unlink()`/`unlink_stale()` in that function is a finding.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, dotted


def _is_create_call(n: ast.AST) -> bool:
    if not isinstance(n, ast.Call):
        return False
    d = dotted(n.func)
    if not d or d.split(".")[-1] != "SharedMemory":
        return False
    for kw in n.keywords:
        if kw.arg == "create" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _has_unlink(scope: ast.AST) -> bool:
    for n in ast.walk(scope):
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "unlink":
                return True
            d = dotted(n.func)
            if d and d.split(".")[-1] == "unlink_stale":
                return True
    return False


class ShmCreateWithoutUnlink(ModuleRule):
    id = "JT-SHM-001"
    doc = ("SharedMemory(create=True) with no unlink path in the "
           "enclosing function — a leaked segment outlives the "
           "process and fills /dev/shm")
    hint = ("pair the create with unlink (happy path) and "
            "unlink_stale (exception path) in the same function — "
            "see shm.export's contract")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        # attribute each create to its INNERMOST enclosing function
        # (the worker fn is the ownership scope), falling back to the
        # module for top-level creates
        def visit(scope: ast.AST, creates: list[ast.Call]):
            for child in ast.iter_child_nodes(scope):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    inner: list[ast.Call] = []
                    visit(child, inner)
                    for c in inner:
                        if not _has_unlink(child):
                            found.append(c)
                else:
                    if _is_create_call(child):
                        creates.append(child)   # type: ignore[arg-type]
                    visit(child, creates)

        found: list[ast.Call] = []
        top: list[ast.Call] = []
        visit(ctx.tree, top)
        for c in top:
            if not _has_unlink(ctx.tree):
                found.append(c)
        for c in found:
            yield self.finding(
                ctx, c, "SharedMemory(create=True) without a lexical "
                        "unlink path")


RULES = [ShmCreateWithoutUnlink()]
