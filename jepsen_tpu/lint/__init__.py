"""jepsen_tpu.lint — the self-hosted static-analysis pass.

The package's core invariants — gates declared once in
`jepsen_tpu.gates`, no host-sync hazards in jitted code, spawn-only
process pools, lexically-paired shm unlink, spans as context managers,
metric names from the declared registry — were enforced only by review
and by runtime failure. Elle's whole thesis (PAPERS.md, arxiv
2003.10554) is that checking artifacts mechanically beats trusting
humans to eyeball them; this module applies that to our own source.

Architecture:

  * `Finding` — one violation: rule id, file:line, message, fix hint;
    machine-readable via `--format json` for CI.
  * module rules (`ModuleRule`) — pure-AST passes over each file of
    the package, grouped in rule families: JT-GATE (env-gate
    registry), JT-JAX (host-sync/recompile hazards), JT-THREAD
    (concurrency discipline), JT-SHM (shared-memory lifecycle),
    JT-TRACE (tracer/span + metric-name discipline), JT-DUR
    (store-artifact durability protocols over the fileflow pass).
  * project rules (`ProjectRule`) — whole-repo checks that need more
    than one file: the README env-gate table must match the registry
    render; every registered gate must appear in test coverage.
  * suppressions — inline `# jt-lint: ok JT-XXX-000 (reason)` on the
    offending line (or alone on the line above) for sanctioned
    sites, and a repo-level `lint_baseline.json` of justified
    `{rule, path, max, reason}` entries for grandfathered debt. A
    baseline entry that no longer matches anything is reported as
    stale so suppressions can only shrink.

The linter is itself tier-1: `tests/test_lint.py` runs it over
`jepsen_tpu/` at every commit (the self-hosting contract), and
`python -m jepsen_tpu.cli lint` / `make lint` expose the same pass to
CI with the standard exit codes (0 clean, 1 findings, 254 usage).
Stdlib-only: `ast` + `re`, no third-party dependencies, and target
files are parsed, never imported or executed.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator

__all__ = [
    "Finding", "ModuleCtx", "ModuleRule", "ProjectRule", "ProjectCtx",
    "all_rules", "rule_ids", "rule_table", "render_rule_block",
    "lint_paths", "lint_project", "apply_baseline", "load_baseline",
    "LintCache", "changed_files", "family_of", "main",
]


def family_of(rule_id: str) -> str:
    """'JT-GATE' for 'JT-GATE-001' — the per-family bench rollup key.
    Ids without a numeric suffix (the JT-PARSE sentinel) are their own
    family."""
    head, _, tail = rule_id.rpartition("-")
    return head if head and tail.isdigit() else rule_id


def findings_by_family(findings: list["Finding"]) -> dict[str, int]:
    """Open findings rolled up per family, every registered family
    present (zero-seeded) — the ONE rollup `lint --format json` and
    bench.py's lint block both emit, so the two can't drift."""
    fams = {family_of(i): 0 for i in rule_ids()}
    for f in findings:
        fams[family_of(f.rule)] = fams.get(family_of(f.rule), 0) + 1
    return dict(sorted(fams.items()))


@dataclass(frozen=True)
class Finding:
    """One violation: where, which rule, what, and how to fix it."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    hint: str = ""

    def render(self) -> str:
        h = f"  [fix: {self.hint}]" if self.hint else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{h}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint}


# ---------------------------------------------------------------------------
# Per-module context: one parse, shared by every rule.
# ---------------------------------------------------------------------------

#: `# jt-lint: ok JT-GATE-001 (why)` — rule ids may be comma-separated;
#: a family prefix (`JT-GATE`) suppresses the whole family on that line.
_SUPPRESS_RE = re.compile(
    r"#\s*jt-lint:\s*ok\s+([A-Z][A-Z0-9-]*(?:\s*,\s*[A-Z][A-Z0-9-]*)*)")


class ModuleCtx:
    """One target file: source, AST, per-line suppressions."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        self.lines = source.splitlines()
        # line number -> set of suppressed rule-id/family strings
        self.suppressions: dict[int, set[str]] = {}
        for i, ln in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(ln)
            if not m:
                continue
            ids = {s.strip() for s in m.group(1).split(",")}
            self.suppressions.setdefault(i, set()).update(ids)
            # a comment-only line suppresses the line below it too
            if ln.lstrip().startswith("#"):
                self.suppressions.setdefault(i + 1, set()).update(ids)

    def suppressed(self, f: Finding) -> bool:
        ids = self.suppressions.get(f.line)
        if not ids:
            return False
        return any(f.rule == s or f.rule.startswith(s + "-") for s in ids)


class ProjectCtx:
    """Whole-repo context for project rules: the repo root plus the
    already-parsed package modules."""

    def __init__(self, root: Path, modules: list[ModuleCtx]):
        self.root = root
        self.modules = modules
        self._by_rel: dict[str, ModuleCtx | None] | None = None

    def module(self, rel: str) -> ModuleCtx | None:
        """The parsed ModuleCtx for a repo-relative path — ONE parse
        per file per run, shared by every rule family (rules_abi and
        wireflow used to each re-parse their targets). Falls back to
        a disk parse when the path wasn't in the module-rule walk
        (`--changed` mode's empty-modules ProjectCtx, fixture trees);
        missing or unparseable files degrade to None, never raise."""
        if self._by_rel is None:
            self._by_rel = {m.rel: m for m in self.modules}
        if rel not in self._by_rel:
            p = self.root / rel
            m: ModuleCtx | None = None
            if p.is_file():
                try:
                    m = _load_ctx(p, self.root)
                except LintParseError:
                    m = None
            self._by_rel[rel] = m
        return self._by_rel[rel]


class ModuleRule:
    """A per-file AST pass. Subclasses set `id`/`hint` and implement
    `check(ctx)` yielding Findings."""

    id: str = ""
    hint: str = ""
    doc: str = ""

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleCtx, node: ast.AST | int,
                message: str) -> Finding:
        line = node if isinstance(node, int) \
            else getattr(node, "lineno", 1)
        return Finding(self.id, ctx.rel, line, message, self.hint)


class ProjectRule:
    """A whole-repo pass (README drift, test coverage)."""

    id: str = ""
    hint: str = ""
    doc: str = ""

    def check_project(self, ctx: ProjectCtx) -> Iterator[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# AST helpers shared by the rule modules.
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> str | None:
    """`a.b.c` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def call_name(node: ast.Call) -> str | None:
    """Dotted name of a call's callee, if it is a plain name chain."""
    return dotted(node.func)


def walk_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node  # type: ignore[misc]


# ---------------------------------------------------------------------------
# Rule registry.
# ---------------------------------------------------------------------------

def all_rules() -> tuple[list[ModuleRule], list[ProjectRule]]:
    """Every registered rule instance (module rules, project rules)."""
    from . import (order, rules_abi, rules_concurrency, rules_dur,
                   rules_gates, rules_jax, rules_lock, rules_meta,
                   rules_shm, rules_tensor, rules_trace, wireflow)
    mod: list[ModuleRule] = []
    proj: list[ProjectRule] = []
    for m in (rules_gates, rules_jax, rules_concurrency, rules_shm,
              rules_trace, rules_abi, rules_tensor, rules_lock,
              rules_dur, order, wireflow, rules_meta):
        for r in m.RULES:
            (proj if isinstance(r, ProjectRule) else mod).append(r)
    return mod, proj


def rule_ids() -> list[str]:
    mod, proj = all_rules()
    return sorted(r.id for r in mod + proj)


def rule_table() -> list[dict]:
    """id/doc/hint rows for the README rule-id table and --list-rules."""
    mod, proj = all_rules()
    return [{"id": r.id, "doc": r.doc, "hint": r.hint}
            for r in sorted(mod + proj, key=lambda r: r.id)]


#: README markers for the generated rule table (the env-gate table's
#: pattern: edit the rules, run `make rule-table`, JT-META-001 fails
#: the build on drift).
RULES_BEGIN = "<!-- lint-rules:begin (generated by jepsen_tpu.lint) -->"
RULES_END = "<!-- lint-rules:end -->"


def render_rule_table() -> str:
    rows = ["| rule | checks |", "|---|---|"]
    for r in rule_table():
        rows.append(f"| {r['id']} | {' '.join(r['doc'].split())} |")
    return "\n".join(rows)


def render_rule_block() -> str:
    return f"{RULES_BEGIN}\n{render_rule_table()}\n{RULES_END}"


# ---------------------------------------------------------------------------
# Runners.
# ---------------------------------------------------------------------------

#: Files exempt from everything: generated/vendored trees would go
#: here. (The package has none today.)
_SKIP_PARTS = {"__pycache__"}


def iter_py_files(base: Path) -> Iterator[Path]:
    for p in sorted(base.rglob("*.py")):
        if not _SKIP_PARTS.intersection(p.parts):
            yield p


def _load_ctx(path: Path, root: Path) -> ModuleCtx | None:
    try:
        src = path.read_text(encoding="utf-8")
        rel = path.resolve().relative_to(root.resolve()).as_posix() \
            if path.resolve().is_relative_to(root.resolve()) \
            else path.as_posix()
        return ModuleCtx(path, rel, src)
    except (OSError, SyntaxError, ValueError) as e:
        # a file the linter cannot parse is itself a finding, surfaced
        # by the caller via the sentinel
        raise LintParseError(path, e) from e


class LintParseError(Exception):
    def __init__(self, path: Path, err: Exception):
        super().__init__(f"{path}: {err}")
        self.path = path
        self.err = err


def lint_paths(paths: Iterable[Path], root: Path,
               rules: list[ModuleRule] | None = None,
               cache: "LintCache | None" = None) -> list[Finding]:
    """Run the module rules over explicit files (fixture tests use
    this); inline suppressions apply, the baseline does not. With a
    `cache`, per-file results are keyed by content hash + engine
    fingerprint — a clean re-run of an unchanged file costs one hash."""
    if rules is None:
        rules, _ = all_rules()
    out: list[Finding] = []
    for p in paths:
        p = Path(p)
        if cache is not None:
            cached = cache.get(p)
            if cached is not None:
                out.extend(cached)
                continue
        try:
            ctx = _load_ctx(p, root)
        except LintParseError as e:
            out.append(Finding("JT-PARSE", str(e.path), 1,
                               f"unparseable: {e.err}",
                               "fix the syntax error"))
            continue
        found = [f for r in rules for f in r.check(ctx)
                 if not ctx.suppressed(f)]
        if cache is not None:
            cache.put(p, found)
        out.extend(found)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_project(root: Path, package_dir: Path | None = None,
                 cache: "LintCache | None" = None) -> list[Finding]:
    """The full pass: module rules over every file of the package,
    then the project rules (README drift, gate test coverage, the ABI
    prover). Baseline NOT yet applied — see `apply_baseline`. With a
    `cache`, unchanged files are served from the content-hash store
    (sound: module-rule findings are a pure function of file bytes +
    the engine fingerprint); project rules always run fresh."""
    root = Path(root)
    if package_dir is None:
        package_dir = root / "jepsen_tpu"
    mod_rules, proj_rules = all_rules()
    findings: list[Finding] = []
    modules: list[ModuleCtx] = []
    for p in iter_py_files(package_dir):
        try:
            ctx = _load_ctx(p, root)
        except LintParseError as e:
            findings.append(Finding("JT-PARSE", str(e.path), 1,
                                    f"unparseable: {e.err}",
                                    "fix the syntax error"))
            continue
        # the ctx is built even on a cache hit (parsing is the cheap
        # part): ProjectCtx.modules must stay COMPLETE — a project
        # rule iterating it on a warm cache would otherwise silently
        # see only the dirty files
        modules.append(ctx)
        cached = cache.get(p) if cache is not None else None
        if cached is not None:
            findings.extend(cached)
            continue
        found = [f for r in mod_rules for f in r.check(ctx)
                 if not ctx.suppressed(f)]
        if cache is not None:
            cache.put(p, found)
        findings.extend(found)
    pctx = ProjectCtx(root, modules)
    for r in proj_rules:
        findings.extend(r.check_project(pctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# Incremental mode: --changed + the content-hash result cache.
# ---------------------------------------------------------------------------

#: Out-of-package inputs module rules consult at check time: the gate
#: registry (JT-GATE-002), the declared metric names (JT-TRACE-002),
#: the typed event kinds (JT-TRACE-003). Editing any of these must
#: invalidate the cache exactly like editing a rule would.
_RULE_INPUT_SOURCES = ("gates.py", "trace.py", "obs/events.py")


def _engine_fingerprint() -> str:
    """Hash of everything that determines a file's findings besides
    the file itself: the lint engine's own sources plus the registry
    modules the rules consult (`_RULE_INPUT_SOURCES`). The cache can
    never serve findings from an older rule set or registry."""
    import hashlib
    h = hashlib.sha256()
    lint_dir = Path(__file__).resolve().parent
    for p in sorted(lint_dir.glob("*.py")):
        h.update(p.name.encode())
        h.update(p.read_bytes())
    for rel in _RULE_INPUT_SOURCES:
        p = lint_dir.parent / rel
        h.update(rel.encode())
        try:
            h.update(p.read_bytes())
        except OSError:
            h.update(b"<absent>")
    return h.hexdigest()[:16]


class LintCache:
    """Per-file module-rule results under
    `bench_artifacts/.lintcache/`, keyed by sha256(engine fingerprint
    + root-relative path + file bytes). The path is part of the key
    because findings are NOT a pure function of content: path-scoped
    rules (hot-path files, kernel modules, the gates-file exemption)
    fire differently for byte-identical files at different locations,
    and the findings themselves embed the path. Best-effort on every
    other axis: an unreadable or corrupt entry is a miss, a failed
    write is ignored — the cache can only make a run faster, never
    wrong."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.dir = self.root / "bench_artifacts" / ".lintcache"
        self._fp = _engine_fingerprint()
        # get() then put() on a miss must not hash the file twice:
        # the key is memoized per path for this run's lifetime
        self._keys: dict[str, str | None] = {}
        self.hits = 0
        self.misses = 0

    def _key(self, path: Path) -> str | None:
        import hashlib
        memo = str(path)
        if memo in self._keys:
            return self._keys[memo]
        try:
            rel = path.resolve().relative_to(
                self.root.resolve()).as_posix() \
                if path.resolve().is_relative_to(self.root.resolve()) \
                else path.as_posix()
            h = hashlib.sha256(self._fp.encode())
            h.update(rel.encode())
            h.update(b"\0")
            h.update(path.read_bytes())
            key = h.hexdigest()
        except OSError:
            key = None
        self._keys[memo] = key
        return key

    def get(self, path: Path) -> list[Finding] | None:
        key = self._key(path)
        if key is None:
            return None
        try:
            data = json.loads((self.dir / f"{key}.json").read_text())
            out = [Finding(**f) for f in data]
        except (OSError, TypeError, ValueError):
            self.misses += 1
            return None
        self.hits += 1
        return out

    def put(self, path: Path, findings: list[Finding]) -> None:
        key = self._key(path)
        if key is None:
            return
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            tmp = self.dir / f".{key}.{os.getpid()}.tmp"
            tmp.write_text(json.dumps([f.as_dict() for f in findings]))
            os.replace(tmp, self.dir / f"{key}.json")
        except OSError:
            pass


def changed_files(root: Path) -> list[Path] | None:
    """Package .py files dirty vs the merge-base with the upstream
    branch (falling back to origin/main, main, then plain HEAD for a
    detached checkout), plus untracked files. None when git itself is
    unavailable — callers degrade to the full run."""
    import subprocess

    def git(*args: str):
        try:
            return subprocess.run(
                ["git", "-C", str(root), *args],
                capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None

    base = "HEAD"
    for ref in ("@{upstream}", "origin/main", "main"):
        r = git("merge-base", "HEAD", ref)
        if r is None:
            return None
        if r.returncode == 0:
            base = r.stdout.strip()
            break
    r = git("diff", "--name-only", base)
    if r is None or r.returncode != 0:
        return None
    names = set(r.stdout.split())
    r = git("ls-files", "--others", "--exclude-standard")
    if r is not None and r.returncode == 0:
        names.update(r.stdout.split())
    out = []
    for n in sorted(names):
        p = Path(root) / n
        if n.endswith(".py") and n.startswith("jepsen_tpu/") \
                and p.is_file() \
                and not _SKIP_PARTS.intersection(p.parts):
            out.append(p)
    return out


# ---------------------------------------------------------------------------
# Baseline.
# ---------------------------------------------------------------------------

@dataclass
class BaselineResult:
    kept: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    stale: list[dict] = field(default_factory=list)


def load_baseline(path: Path) -> list[dict]:
    """`lint_baseline.json` entries: {rule, path, max, reason}. Every
    entry MUST carry a non-empty reason — an unjustified suppression
    is rejected (that's the point of the file)."""
    try:
        data = json.loads(Path(path).read_text())
    except FileNotFoundError:
        return []
    entries = data.get("entries", []) if isinstance(data, dict) else data
    out = []
    for e in entries:
        if not isinstance(e, dict) or not e.get("rule") \
                or not e.get("path") or not str(e.get("reason", "")).strip():
            raise ValueError(
                f"baseline entry needs rule/path/reason: {e!r}")
        out.append({"rule": e["rule"], "path": e["path"],
                    "max": int(e.get("max", 1)),
                    "reason": str(e["reason"])})
    return out


def apply_baseline(findings: list[Finding],
                   entries: list[dict]) -> BaselineResult:
    """Suppress up to `max` findings per (rule, path) entry; entries
    that match nothing are reported stale (suppressions must shrink,
    not accrete)."""
    res = BaselineResult()
    budget: dict[tuple[str, str], int] = {}
    for e in entries:
        budget[(e["rule"], e["path"])] = \
            budget.get((e["rule"], e["path"]), 0) + e["max"]
    used: dict[tuple[str, str], int] = {}
    for f in findings:
        key = (f.rule, f.path)
        if used.get(key, 0) < budget.get(key, 0):
            used[key] = used.get(key, 0) + 1
            res.suppressed.append(f)
        else:
            res.kept.append(f)
    for e in entries:
        if used.get((e["rule"], e["path"]), 0) == 0:
            res.stale.append(e)
    return res


# ---------------------------------------------------------------------------
# CLI entry (`python -m jepsen_tpu.cli lint` and `python -m
# jepsen_tpu.lint` both land here).
# ---------------------------------------------------------------------------

def default_root() -> Path:
    """The repo root: the directory holding the `jepsen_tpu` package."""
    return Path(__file__).resolve().parents[2]


def run(paths: list[str] | None = None, *, root: Path | None = None,
        baseline: str | None = None, fmt: str = "text",
        changed: bool = False, out=None) -> int:
    """The lint run behind the CLI. Returns the exit code (0 clean,
    1 findings). `paths`: explicit files/dirs to lint with the module
    rules only; default is the full project pass (module + project
    rules + baseline). `changed` analyzes only files dirty vs the git
    merge-base through the content-hash result cache — the fast inner
    loop; the full run stays the tier-1 default."""
    out = out if out is not None else sys.stdout
    root = Path(root) if root is not None else default_root()
    cache_line = ""
    if paths:
        files: list[Path] = []
        for p in paths:
            pp = Path(p)
            files.extend(iter_py_files(pp) if pp.is_dir() else [pp])
        findings = lint_paths(files, root)
        res = BaselineResult(kept=findings)
        entries: list[dict] = []
    else:
        if changed:
            dirty = changed_files(root)
            if dirty is None:
                print("lint: --changed needs git; running the full "
                      "pass", file=sys.stderr)
                findings = lint_project(root)
            else:
                cache = LintCache(root)
                findings = lint_paths(dirty, root, cache=cache)
                mod_rules, proj_rules = all_rules()
                pctx = ProjectCtx(root, [])
                for r in proj_rules:
                    findings.extend(r.check_project(pctx))
                findings.sort(key=lambda f: (f.path, f.line, f.rule))
                cache_line = (f"lint: --changed: {len(dirty)} dirty "
                              f"file(s), cache {cache.hits} hit(s)")
        else:
            findings = lint_project(root, cache=LintCache(root))
        bpath = Path(baseline) if baseline \
            else root / "lint_baseline.json"
        try:
            entries = load_baseline(bpath)
        except ValueError as e:
            print(f"lint: bad baseline: {e}", file=sys.stderr)
            return 254
        res = apply_baseline(findings, entries)
        if changed:
            # a partial view cannot judge staleness: an entry whose
            # file simply wasn't dirty would be reported dead
            res.stale = []

    if fmt == "json":
        print(json.dumps({
            "findings": [f.as_dict() for f in res.kept],
            "findings_by_family": findings_by_family(res.kept),
            "suppressed": len(res.suppressed),
            "baseline_entries": len(entries),
            "baseline_stale": res.stale,
            "rules": len(rule_ids()),
        }, indent=2), file=out)
    else:
        for f in res.kept:
            print(f.render(), file=out)
        for e in res.stale:
            print(f"lint: stale baseline entry (matched nothing): "
                  f"{e['rule']} {e['path']} — remove it", file=out)
        if cache_line:
            print(cache_line, file=out)
        n = len(res.kept)
        print(f"lint: {n} finding{'s' if n != 1 else ''} "
              f"({len(res.suppressed)} baseline-suppressed, "
              f"{len(rule_ids())} rules)", file=out)
    # stale baseline entries are findings too: the exit code is what
    # makes "the baseline can only shrink" enforceable from one command
    return 1 if res.kept or res.stale else 0


def add_args(p) -> None:
    """The lint CLI surface, defined ONCE — both entry points
    (`python -m jepsen_tpu.lint` and the `lint` subcommand of
    `python -m jepsen_tpu.cli`) build their parser from here, so the
    two documented commands cannot drift apart."""
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint with the module rules only "
                        "(default: the whole package + project rules + "
                        "baseline)")
    p.add_argument("--format", choices=("text", "json"),
                   default="text", dest="lint_format")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default lint_baseline.json at "
                        "the repo root)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--changed", action="store_true",
                   help="analyze only files dirty vs the git "
                        "merge-base, through the content-hash result "
                        "cache (bench_artifacts/.lintcache); project "
                        "rules still run in full")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")


def run_from_args(args) -> int:
    """Dispatch a namespace produced by an `add_args` parser."""
    if args.list_rules:
        if args.lint_format == "json":
            print(json.dumps(rule_table(), indent=2))
        else:
            for r in rule_table():
                print(f"{r['id']}: {r['doc']}")
        return 0
    return run(args.paths or None, root=args.root,
               baseline=args.baseline, fmt=args.lint_format,
               changed=getattr(args, "changed", False))


def main(argv: list[str] | None = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="jepsen-tpu lint",
        description="self-hosted static analysis (gate registry, JAX "
                    "hazards, concurrency, shm lifecycle, tracer "
                    "discipline)")
    add_args(p)
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 254 if e.code not in (0, None) else 0
    return run_from_args(args)
