"""JT-THREAD — concurrency discipline.

The hot path is three threads (dispatcher / pack-h2d / watchdog) over
process pools; the failure modes this family polices are exactly the
ones already hit and fixed in this tree: `multiprocessing.Pool` hangs
forever on a SIGKILLed worker (PR 4 moved every pool to
`ProcessPoolExecutor` + spawn), fork-starting workers from a process
with live threads deadlocks in the child, a bare `.acquire()` leaks
the lock on any exception path, and out-of-API writes to tracer
internals race the recording threads.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, ModuleCtx, ModuleRule, const_str, dotted

_TRACE_FILE = "jepsen_tpu/trace.py"
_LOCK_CTORS = {"Lock", "RLock"}
_TRACERISH = {"tr", "tracer"}


class MpPool(ModuleRule):
    id = "JT-THREAD-001"
    doc = ("multiprocessing.Pool usage — a worker that dies without "
           "delivering (SIGKILL, OOM killer) hangs imap forever; the "
           "exact bug class PR 4 removed")
    hint = ("use concurrent.futures.ProcessPoolExecutor with "
            "mp_context=get_context('spawn') — a dead worker raises "
            "BrokenProcessPool instead of hanging")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "Pool":
                yield self.finding(ctx, n, "multiprocessing-style .Pool()")


class BareLockAcquire(ModuleRule):
    id = "JT-THREAD-002"
    doc = ("bare .acquire() on a threading Lock/RLock — any exception "
           "between acquire and release leaks the lock and wedges "
           "every later waiter")
    hint = "use `with lock:` (or try/finally release at minimum)"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        # names (and attribute names) assigned from Lock()/RLock() —
        # Semaphores/Events acquired bare for flow control don't count
        lock_names: set[str] = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                d = dotted(n.value.func)
                if d and d.split(".")[-1] in _LOCK_CTORS:
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            lock_names.add(t.id)
                        elif isinstance(t, ast.Attribute):
                            lock_names.add(t.attr)
        if not lock_names:
            return
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "acquire":
                recv = n.func.value
                name = recv.id if isinstance(recv, ast.Name) \
                    else recv.attr if isinstance(recv, ast.Attribute) \
                    else None
                if name in lock_names:
                    yield self.finding(
                        ctx, n, f"bare acquire() on lock `{name}`")


class ForkStart(ModuleRule):
    id = "JT-THREAD-003"
    doc = ("fork(server) start method — forking a process with live "
           "threads (dispatcher/pack-h2d/watchdog are always up) "
           "deadlocks the child on whatever locks the threads held")
    hint = "always pass 'spawn': mp.get_context('spawn')"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            tail = d.split(".")[-1] if d else None
            if tail not in ("get_context", "set_start_method"):
                continue
            arg = const_str(n.args[0]) if n.args else None
            if arg is None and not n.args:
                yield self.finding(
                    ctx, n,
                    f"{tail}() without an explicit method defaults to "
                    "fork on Linux")
            elif arg in ("fork", "forkserver"):
                yield self.finding(ctx, n, f"{tail}({arg!r})")


class TracerPrivateAccess(ModuleRule):
    id = "JT-THREAD-004"
    doc = ("access to tracer private state (tr._events, "
           "trace._current, ...) outside trace.py — the recording "
           "threads own those structures; out-of-API writes race them")
    hint = ("go through the trace API (span/add_span/instant/"
            "counter/…, set_current/reset)")

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if ctx.rel.endswith(_TRACE_FILE):
            return
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Attribute):
                continue
            if not (n.attr.startswith("_") and not n.attr.startswith("__")):
                continue
            recv = n.value
            if isinstance(recv, ast.Name) \
                    and (recv.id in _TRACERISH or recv.id == "trace"):
                yield self.finding(
                    ctx, n, f"private tracer state `{recv.id}.{n.attr}`")


RULES = [MpPool(), BareLockAcquire(), ForkStart(),
         TracerPrivateAccess()]
