"""Interprocedural file-effect analysis for the JT-DUR rules.

The unit of tracking is a *store-rooted path*: an expression that
names a file at the store root (or the compile-cache root) — a
``<base> / <literal>`` join, a call to a registered path-constructor
helper (``costdb_path``, ``shard_journal_path``, …), or a local alias
of either. Each resolves to a file-name *skeleton* (interpolated
segments become ``*``) that `contracts.artifact_for_name` maps to a
declared `StoreArtifact` — or to None, which IS the JT-DUR-001
finding.

On top of the path lattice the pass collects the module's *file
effects*, per scope:

  * write effects — ``open(p, "w"/"a"/…)``, ``p.write_text``/
    ``write_bytes`` (``os.replace`` and ``atomic_write_text`` are the
    SANCTIONED publishes and deliberately not effects);
  * read effects — ``p.read_text()``, ``open(p)``;
  * append-handle histories — for every handle opened in append mode
    (``f = open(p, "a")``, ``with open(p, "a") as f``,
    ``self._f = open(…)``), the lexical sequence of its ``write``/
    ``flush``/``close`` calls, which JT-DUR-003 checks against the
    journal discipline (one write per record, flushed before the
    handle can be observed).

Interprocedural on two edges, intraprocedural otherwise (the
`dataflow.py` philosophy — catch the local slip the moment it is
written): calls to registry-declared path helpers resolve to their
artifact anywhere in the repo, and a module-local function whose
`return` is a store-rooted join registers itself as a helper for the
rest of its module. A path that crosses any OTHER call boundary is
out of lexical reach; the crash-sim tests own that residue.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from . import const_str, dotted
from . import contracts
from .dataflow import iter_scopes, own_nodes

__all__ = ["analyze", "ModuleFlow", "ScopeFlow"]

ROOT_STORE = "store"
ROOT_CACHE = "cache"

#: Parameter/variable spellings that ARE a store base. Kept
#: deliberately narrow: `store_base` and `spool_dir` are the
#: package-wide conventions (`store.base`/`self.base` as dotted
#: chains below); a run-dir path (`d`, `run_dir`) never qualifies —
#: the registry governs the store ROOT namespace, run dirs are the
#: run's own.
BASE_NAMES = frozenset({"store_base", "spool_dir"})
BASE_DOTTED = frozenset({"store.base", "self.base"})

#: Calls whose result is the compile-cache root.
CACHE_FNS = frozenset({"cache_dir"})


def module_str_consts(tree: ast.Module) -> dict[str, str]:
    """Module-level `NAME = "literal"` string constants (EVENTS_NAME,
    COSTDB_NAME, SPOOL_PREFIX …) — f-string skeletons resolve through
    these. Imported constants stay opaque (their join is skipped, not
    guessed)."""
    out: dict[str, str] = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            s = const_str(n.value)
            if s is not None:
                out[n.targets[0].id] = s
    return out


def _tail_str(node: ast.AST, consts: dict[str, str]) -> str | None:
    """The file-name skeleton of a join's right operand: a literal, a
    module constant, or an f-string whose interpolations become `*`
    (constants referenced inside resolve through `consts`). A skeleton
    with no leading literal (`*…`) is unresolvable — better to skip a
    fully-dynamic name than to misattribute it."""
    s = const_str(node)
    if s is None and isinstance(node, ast.Name):
        s = consts.get(node.id)
    if s is None and isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            c = const_str(v)
            if c is None and isinstance(v, ast.FormattedValue) \
                    and isinstance(v.value, ast.Name):
                c = consts.get(v.value.id)
            parts.append(c if c is not None else "*")
        s = "".join(parts)
    if s is None or not s or s.startswith("*"):
        return None
    return s


@dataclass
class ScopeFlow:
    """One function's (or the module body's) file effects."""

    qualname: str
    #: every resolved `<base>/<literal>` join: (node, tail, root)
    joins: list[tuple[ast.AST, str, str]] = field(default_factory=list)
    #: open() calls: (node, tail|None, mode)
    opens: list[tuple[ast.Call, str | None, str]] \
        = field(default_factory=list)
    #: write_text/write_bytes on a resolved path: (node, tail)
    write_texts: list[tuple[ast.Call, str]] = field(default_factory=list)
    #: read_text on a resolved path: (node, tail)
    read_texts: list[tuple[ast.Call, str]] = field(default_factory=list)
    #: append-mode handles: spelling -> [(line, kind, node, is_nl)]
    #: where kind in write|flush|close and is_nl marks write("\n")
    handles: dict[str, list[tuple[int, str, ast.AST, bool]]] \
        = field(default_factory=dict)
    has_json_loads: bool = False


@dataclass
class ModuleFlow:
    scopes: list[ScopeFlow] = field(default_factory=list)


def _qualnames(tree: ast.Module) -> dict[int, str]:
    """node id -> qualname, from the ONE def walk the lockset engine
    already owns (cfg.iter_defs) — two traversals with their own
    prefixing rules would drift, and the JT-DUR-004 sanctioned-reader
    exemption keys on these strings."""
    from .cfg import iter_defs
    return {id(n): q for q, _cls, n in iter_defs(tree)}


def _call_tail_name(node: ast.Call) -> str | None:
    d = dotted(node.func)
    return d.split(".")[-1] if d else None


def _is_base(node: ast.AST, base_vars: dict[str, str]) -> str | None:
    """ROOT_STORE/ROOT_CACHE when `node` is a store/cache root
    expression, else None. `Path(<base>)` is transparent."""
    if isinstance(node, ast.Name):
        if node.id in BASE_NAMES:
            return ROOT_STORE
        return base_vars.get(node.id)
    d = dotted(node)
    if d in BASE_DOTTED:
        return ROOT_STORE
    if isinstance(node, ast.Call):
        tn = _call_tail_name(node)
        if tn == "Path" and len(node.args) == 1 and not node.keywords:
            return _is_base(node.args[0], base_vars)
        if tn in CACHE_FNS:
            return ROOT_CACHE
    return None


class _Scope:
    """Per-scope resolution state built by `analyze`."""

    def __init__(self, consts: dict[str, str],
                 helpers: dict[str, tuple[str, str]]):
        self.consts = consts
        self.helpers = helpers
        self.base_vars: dict[str, str] = {}       # name -> root kind
        self.path_vars: dict[str, tuple[str, str]] = {}  # -> (tail, root)

    def resolve(self, node: ast.AST) -> tuple[str, str] | None:
        """(tail skeleton, root) for a store/cache-rooted path expr."""
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            root = _is_base(node.left, self.base_vars)
            if root is None:
                return None
            tail = _tail_str(node.right, self.consts)
            if tail is None:
                return None
            return tail, root
        if isinstance(node, ast.Call):
            tn = _call_tail_name(node)
            if tn is not None and tn in self.helpers:
                return self.helpers[tn]
        if isinstance(node, ast.Name):
            return self.path_vars.get(node.id)
        if isinstance(node, ast.Attribute):
            d = dotted(node)
            if d is not None:
                return self.path_vars.get(d)
        return None


def _open_call(node: ast.AST) -> ast.Call | None:
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "open":
        return node
    return None


def _open_mode(call: ast.Call) -> str | None:
    """The literal mode of an open() call ('r' when omitted), None
    when dynamic."""
    node = call.args[1] if len(call.args) > 1 else None
    if node is None:
        for kw in call.keywords:
            if kw.arg == "mode":
                node = kw.value
    if node is None:
        return "r"
    return const_str(node)


def _registry_helpers() -> dict[str, tuple[str, str]]:
    return {name: (a.patterns[0], a.root)
            for name, a in contracts.PATH_HELPERS.items()}


def _local_helpers(tree: ast.Module, consts: dict[str, str],
                   helpers: dict[str, tuple[str, str]]) -> None:
    """Module-local interprocedural edge: a function whose `return`
    is a store-rooted join acts as a path helper for the rest of its
    module. Registry-declared helpers win on a name collision (their
    patterns are the stable contract)."""
    empty = _Scope(consts, helpers)
    for n in ast.walk(tree):
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or n.name in helpers:
            continue
        for stmt in own_nodes(n):
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                r = empty.resolve(stmt.value)
                if r is not None:
                    helpers[n.name] = r
                    break


def analyze(ctx) -> ModuleFlow:
    """The module's file effects, memoized on the ModuleCtx (all four
    JT-DUR module rules share one pass)."""
    cached = getattr(ctx, "_fileflow", None)
    if cached is not None:
        return cached
    tree = ctx.tree
    consts = module_str_consts(tree)
    helpers = _registry_helpers()
    _local_helpers(tree, consts, helpers)
    quals = _qualnames(tree)
    flow = ModuleFlow()
    for scope in iter_scopes(tree):
        sc = _Scope(consts, helpers)
        out = ScopeFlow(qualname=quals.get(id(scope), ""))
        # two passes so `base = Path(store_base)` then
        # `p = base / NAME` then `open(p, …)` all chain
        for _ in range(2):
            for n in own_nodes(scope):
                if not (isinstance(n, ast.Assign)
                        and len(n.targets) == 1):
                    continue
                t = n.targets[0]
                key = t.id if isinstance(t, ast.Name) else dotted(t)
                if key is None:
                    continue
                root = _is_base(n.value, sc.base_vars)
                if root is not None:
                    sc.base_vars[key] = root
                    continue
                r = sc.resolve(n.value)
                if r is not None:
                    sc.path_vars[key] = r
        for n in own_nodes(scope):
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Div):
                r = sc.resolve(n)
                if r is not None:
                    out.joins.append((n, r[0], r[1]))
            elif isinstance(n, ast.Call):
                oc = _open_call(n)
                if oc is not None and n.args:
                    r = sc.resolve(n.args[0])
                    out.opens.append(
                        (n, r[0] if r else None, _open_mode(n) or ""))
                elif isinstance(n.func, ast.Attribute):
                    at = n.func.attr
                    if at in ("write_text", "write_bytes"):
                        r = sc.resolve(n.func.value)
                        if r is not None:
                            out.write_texts.append((n, r[0]))
                    elif at == "read_text":
                        r = sc.resolve(n.func.value)
                        if r is not None:
                            out.read_texts.append((n, r[0]))
                if dotted(n.func) == "json.loads" \
                        or (isinstance(n.func, ast.Name)
                            and n.func.id == "loads"):
                    out.has_json_loads = True
        _track_handles(scope, out)
        flow.scopes.append(out)
    ctx._fileflow = flow
    return flow


def _track_handles(scope: ast.AST, out: ScopeFlow) -> None:
    """Append-mode handle histories for JT-DUR-003: bind handles from
    `with open(p, "a") as f` / `f = open(p, "a")` / `self._f = open`,
    then record each handle's write/flush/close calls in lexical
    order. EVERY open() binding is collected — append or not — so a
    later rebinding of the same name to a non-append handle ends the
    append handle's region instead of donating its writes to it (a
    `with open(p, "a") as f: ...` followed by `with open(q, "w") as
    f: ...` in one function must not misattribute the second f's
    writes)."""
    bindings: list[tuple[int, str, bool]] = []
    for n in own_nodes(scope):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                c = _open_call(item.context_expr)
                if c is not None and item.optional_vars is not None \
                        and isinstance(item.optional_vars, ast.Name):
                    mode = _open_mode(c)
                    bindings.append(
                        (n.lineno, item.optional_vars.id,
                         mode is not None and "a" in mode))
        elif isinstance(n, ast.Assign) and len(n.targets) == 1:
            c = _open_call(n.value)
            if c is not None:
                t = n.targets[0]
                key = t.id if isinstance(t, ast.Name) else dotted(t)
                if key is not None:
                    mode = _open_mode(c)
                    bindings.append(
                        (n.lineno, key,
                         mode is not None and "a" in mode))
    append_keys = {k for _ln, k, ap in bindings if ap}
    if not append_keys:
        return
    bindings.sort()

    def owned_by_append(key: str, line: int) -> bool:
        """Does the latest binding of `key` at or before `line` hold
        an append handle? Events before any binding stay unowned."""
        owner = None
        for bl, bk, ap in bindings:
            if bk == key and bl <= line:
                owner = ap
        return owner is True

    for n in own_nodes(scope):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)):
            continue
        recv = dotted(n.func.value)
        if recv not in append_keys \
                or not owned_by_append(recv, n.lineno):
            continue
        at = n.func.attr
        if at in ("write", "writelines"):
            is_nl = bool(n.args) and const_str(n.args[0]) == "\n"
            out.handles.setdefault(recv, []).append(
                (n.lineno, "write", n, is_nl))
        elif at in ("flush", "close"):
            out.handles.setdefault(recv, []).append(
                (n.lineno, at, n, False))
    for evs in out.handles.values():
        evs.sort(key=lambda e: e[0])
