"""`python -m jepsen_tpu.lint` — the direct entry point (the CLI's
`lint` subcommand routes to the same `main`)."""
import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
