"""Intraprocedural dataflow for the tensor-contract rules (JT-TENSOR).

The unit of tracking is a *tag*: which declared encoded-tensor field
(contracts.TENSOR_DTYPES) a local expression refers to. Tags seed from
the places a contracted tensor enters a scope — a parameter named
after the field, `enc.appends`, `arrays["reads"]`, a `np.full` built
into a field-named variable — and propagate through assignment chains
and the dtype-preserving wrappers (`asarray`, `ascontiguousarray`,
`astype`, `reshape`, slicing). The rules then ask one question per
call site: "is this expression a contracted tensor, and does the
operation respect its declared dtype/fill/shape?"

Deliberately intraprocedural: a tag never crosses a call boundary.
That keeps the analysis O(module) and false-positive-shy — the
cross-function contracts are pinned by the runtime parity tests; what
static analysis adds is catching the LOCAL slip (a stray `.astype`, a
wrong fill) the moment it is written.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import const_str, dotted
from . import contracts

__all__ = [
    "resolve_dtype", "module_int_consts", "int_value", "build_tags",
    "tag_of", "iter_scopes",
]

_NP_NAMES = {"np", "numpy", "jnp", "onp"}

#: Wrappers through which a tag survives: f(x, ...) tags like x.
_TAG_TRANSPARENT = {"asarray", "ascontiguousarray", "array",
                    "require"}
#: Methods through which a tag survives: x.m(...) tags like x.
_TAG_METHODS = {"astype", "reshape", "copy", "view", "ravel"}


def resolve_dtype(node: ast.AST | None) -> str | None:
    """'int32' for np.int32 / jnp.int32 / "int32" / np.dtype(np.int32);
    None when not statically resolvable."""
    if node is None:
        return None
    s = const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id in _NP_NAMES:
        return node.attr
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        if d and d.split(".")[-1] == "dtype" and node.args:
            return resolve_dtype(node.args[0])
    return None


def module_int_consts(tree: ast.Module) -> dict[str, int]:
    """Module-level `NAME = <int literal or simple arithmetic>`."""
    out: dict[str, int] = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            v = int_value(n.value, {})
            if v is not None:
                out[n.targets[0].id] = v
    return out


def int_value(node: ast.AST, consts: dict[str, int]) -> int | None:
    """A statically-known int: literal, +/- literal, module constant,
    or a product/shift of those."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = int_value(node.operand, consts)
        return -v if v is not None else None
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.BinOp) \
            and isinstance(node.op, (ast.Mult, ast.LShift, ast.Add)):
        lt = int_value(node.left, consts)
        rt = int_value(node.right, consts)
        if lt is None or rt is None:
            return None
        if isinstance(node.op, ast.Mult):
            return lt * rt
        if isinstance(node.op, ast.Add):
            return lt + rt
        return lt << rt
    return None


def _field_from_name(name: str) -> str | None:
    return contracts.field_of(name)


def tag_of(node: ast.AST, tags: dict[str, str]) -> str | None:
    """The declared field `node` refers to under the current tag
    environment, looking through the dtype-preserving wrappers."""
    if isinstance(node, ast.Name):
        return tags.get(node.id) or _field_from_name(node.id)
    if isinstance(node, ast.Attribute):
        # enc.appends / self.reads — the attribute name IS the field
        return _field_from_name(node.attr)
    if isinstance(node, ast.Subscript):
        if const_str(node.slice) is not None:
            # arrays["appends"]
            return _field_from_name(const_str(node.slice))
        return tag_of(node.value, tags)   # x[:n] keeps x's tag
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) \
                    and f.value.id in _NP_NAMES \
                    and f.attr in _TAG_TRANSPARENT and node.args:
                return tag_of(node.args[0], tags)
            if f.attr in _TAG_METHODS:
                return tag_of(f.value, tags)
    return None


def build_tags(scope: ast.AST) -> dict[str, str]:
    """name → declared field, for one function (or module) scope.
    Two passes so one level of `y = x` chaining resolves; parameters
    named after a field (or its registered alias) seed the map."""
    tags: dict[str, str] = {}
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = scope.args
        for p in (a.posonlyargs + a.args + a.kwonlyargs):
            f = _field_from_name(p.arg)
            if f:
                tags[p.arg] = f
    for _ in range(2):
        for n in own_nodes(scope):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1):
                continue
            t = n.targets[0]
            if not isinstance(t, ast.Name):
                continue
            f = tag_of(n.value, tags)
            if f is None:
                # a field-named target built from an array ctor
                # (np.full/zeros) adopts its own name's contract
                if isinstance(n.value, ast.Call):
                    d = dotted(n.value.func)
                    if d and d.split(".")[0] in _NP_NAMES:
                        f = _field_from_name(t.id)
            if f is not None:
                tags[t.id] = f
            elif t.id in tags and _field_from_name(t.id) is None:
                # rebound to something un-tagged: drop the stale tag
                del tags[t.id]
    return tags


def iter_scopes(tree: ast.Module) -> Iterator[ast.AST]:
    """Each function in the module plus the module itself; pair with
    `own_nodes` so every node is analyzed exactly once, under its
    nearest enclosing scope's tag environment."""
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n
    yield tree


def own_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """The nodes belonging to `scope` itself — the walk stops at
    nested function (and lambda) boundaries: their bodies are their
    own scopes, with their own bindings. The ONE stop-at-nested-defs
    traversal, shared by the tensor and lock rule families."""
    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield child
            yield from walk(child)

    yield from walk(scope)
