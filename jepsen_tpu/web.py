"""L7 UI: a web browser for the store.

Counterpart of jepsen.web (jepsen/src/jepsen/web.clj): a table of runs
(web.clj:122), per-run directory listings (207), zip export of a run
(258-299), and a path-traversal guard (300) — built on http.server, no
dependencies.
"""

from __future__ import annotations

import html
import io
import json
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import quote, unquote

from .store import Store

CONTENT_TYPES = {
    ".txt": "text/plain", ".edn": "text/plain", ".log": "text/plain",
    ".json": "application/json", ".jsonl": "application/json",
    ".html": "text/html", ".svg": "image/svg+xml", ".png": "image/png",
    ".pcap": "application/vnd.tcpdump.pcap",
}


def _page(title: str, body: str) -> bytes:
    return (f"<!doctype html><html><head><title>{html.escape(title)}</title>"
            "<style>body{font-family:monospace;margin:2em} "
            "table{border-collapse:collapse} td,th{padding:.3em .8em;"
            "border-bottom:1px solid #ddd;text-align:left}</style>"
            f"</head><body><h1>{html.escape(title)}</h1>{body}"
            "</body></html>").encode()


def _valid_str(results: dict | None) -> str:
    if results is None:
        return "?"
    v = results.get("valid?")
    return {True: "valid", False: "INVALID"}.get(v, "unknown")


class StoreHandler(BaseHTTPRequestHandler):
    store: Store = None  # set by serve()

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes,
              ctype: str = "text/html") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _safe_path(self, rel: str) -> Path | None:
        """Resolve rel under the store root; None if it escapes
        (web.clj:300 traversal guard)."""
        base = self.store.base.resolve()
        p = (base / rel).resolve()
        return p if p == base or base in p.parents else None

    def do_GET(self):
        path = unquote(self.path.split("?")[0]).lstrip("/")
        if path == "":
            return self._home()
        if path.startswith("zip/"):
            return self._zip(path[4:])
        if path.startswith("files/"):
            return self._files(path[6:])
        self._send(404, _page("404", "<p>not found</p>"))

    def _home(self):
        rows = []
        for name, runs in sorted(self.store.tests().items()):
            for start, d in sorted(runs.items(), reverse=True):
                results = self.store.load_results(d)
                rel = f"{name}/{start}"
                rows.append(
                    f"<tr><td><a href='/files/{quote(rel)}'>"
                    f"{html.escape(name)}</a></td>"
                    f"<td>{html.escape(start)}</td>"
                    f"<td>{_valid_str(results)}</td>"
                    f"<td><a href='/zip/{quote(rel)}'>zip</a></td></tr>")
        body = ("<table><tr><th>test</th><th>time</th><th>valid?</th>"
                "<th></th></tr>" + "".join(rows) + "</table>")
        self._send(200, _page("jepsen-tpu store", body))

    def _files(self, rel: str):
        p = self._safe_path(rel)
        if p is None or not p.exists():
            return self._send(404, _page("404", "<p>not found</p>"))
        if p.is_dir():
            entries = sorted(p.iterdir())
            items = "".join(
                f"<li><a href='/files/{quote(rel)}/{quote(e.name)}'>"
                f"{html.escape(e.name)}{'/' if e.is_dir() else ''}</a></li>"
                for e in entries)
            return self._send(200, _page(rel, f"<ul>{items}</ul>"))
        ctype = CONTENT_TYPES.get(p.suffix, "application/octet-stream")
        self._send(200, p.read_bytes(), ctype)

    def _zip(self, rel: str):
        p = self._safe_path(rel)
        if p is None or not p.is_dir():
            return self._send(404, _page("404", "<p>not found</p>"))
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for f in sorted(p.rglob("*")):
                if f.is_file():
                    z.write(f, f.relative_to(p.parent))
        self._send(200, buf.getvalue(), "application/zip")


def make_server(store: Store, host: str = "127.0.0.1",
                port: int = 8080) -> ThreadingHTTPServer:
    handler = type("BoundStoreHandler", (StoreHandler,), {"store": store})
    return ThreadingHTTPServer((host, port), handler)


def serve(store: Store, host: str = "0.0.0.0", port: int = 8080) -> None:
    srv = make_server(store, host, port)
    print(f"serving {store.base} on http://{host}:{port}")
    try:
        srv.serve_forever()
    finally:
        srv.server_close()
