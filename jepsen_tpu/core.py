"""L5: the test runner — full lifecycle orchestration.

Counterpart of jepsen.core (jepsen/src/jepsen/core.clj): `run(test)`
provisions the OS and DB over the control plane, sets up clients and the
nemesis, evaluates the generator through the interpreter while capturing
a history, persists everything, analyzes it with the test's checker, and
tears the world down again (run! core.clj:530-637; analyze! 496-513).

A test is a plain dict — the universal config object (core.clj:531-554):

    {"name":        str
     "nodes":       ["n1", ...]
     "concurrency": int                    # client worker count
     "ssh":         {"username", "port", "dummy", ...}
     "os":          OS                     # os_setup.OS
     "db":          DB                     # db.DB
     "client":      Client                 # client.Client
     "nemesis":     Nemesis                # nemesis.Nemesis
     "generator":   generator              # generator DSL value
     "checker":     Checker                # checker.Checker
     "store":       Store (optional)
     "leave_db_running": bool}
"""

from __future__ import annotations

import datetime
import logging
import os as _os
from typing import Any

from . import checker as jchecker
from . import client as jclient
from . import control, db as jdb, history as jhistory, os_setup, trace
from .generator import interpreter
from .store import Store
from .util import real_pmap, relative_time

log = logging.getLogger(__name__)

DEFAULTS = {
    "name": "noname",
    "nodes": ["n1", "n2", "n3", "n4", "n5"],
    "concurrency": 5,
    "ssh": {},
    "leave_db_running": False,
}


def prepare_test(test: dict) -> dict:
    """Fill in defaults; resolve concurrency "2n" syntax
    (cli.clj:138-153)."""
    t = {**DEFAULTS, **test}
    conc = t.get("concurrency")
    if isinstance(conc, str):
        if conc.endswith("n"):
            mult = conc[:-1] or "1"
            t["concurrency"] = int(mult) * len(t["nodes"])
        else:
            t["concurrency"] = int(conc)
    t.setdefault("os", os_setup.noop())
    t.setdefault("db", jdb.noop())
    t.setdefault("client", jclient.noop())
    t.setdefault("checker", jchecker.unbridled_optimism())
    if "start-time" not in t:
        t["start-time"] = datetime.datetime.now().strftime(
            "%Y%m%dT%H%M%S.%f")[:-3]
    return t


def setup_clients(test: dict) -> list:
    """Open one client per node and run setup (core.clj:457-476)."""
    base = test.get("client")

    def setup1(node):
        c = base.open(test, node)
        try:
            c.setup(test)
        finally:
            c.close(test)

    real_pmap(setup1, test.get("nodes", []))
    return []


def teardown_clients(test: dict) -> None:
    base = test.get("client")

    def teardown1(node):
        c = base.open(test, node)
        try:
            c.teardown(test)
        finally:
            c.close(test)

    try:
        real_pmap(teardown1, test.get("nodes", []))
    except Exception as e:
        log.warning("client teardown failed: %s", e)


def snarf_logs(test: dict) -> None:
    """Download DB log files from each node into the store
    (core.clj:103-137)."""
    db = test.get("db")
    if not isinstance(db, jdb.LogFiles):
        return
    store: Store = test["store"]

    def snarf1(t, node):
        sess = control.current_session()
        for f in db.log_files(t, node):
            dest = store.path(t, node, _os.path.basename(f))
            try:
                sess.download(f, str(dest))
            except Exception as e:
                log.warning("couldn't snarf %s from %s: %s", f, node, e)

    try:
        control.on_nodes(test, snarf1)
    except Exception as e:
        log.warning("log snarfing failed: %s", e)


def analyze(test: dict) -> dict:
    """Index the history, run the checker, persist results
    (analyze! core.clj:496-513)."""
    log.info("Analyzing...")
    test["history"] = jhistory.index(test.get("history", []))
    with trace.span("analyze", ops=len(test["history"])):
        results = jchecker.check_safe(
            test.get("checker") or jchecker.unbridled_optimism(),
            test, test["history"], {})
    test["results"] = results
    store: Store = test.get("store") or Store()
    test["store"] = store
    store.save_2(test)
    log.info("Analysis complete: valid? = %r", results.get("valid?"))
    return test


def run(test: dict) -> dict:
    """Run a complete test; returns the test with :history and :results
    (run! core.clj:530-637)."""
    test = prepare_test(test)
    store: Store = test.get("store") or Store()
    test["store"] = store
    log.info("Running test %s", test["name"])
    # A fresh per-run tracer: trace.json/metrics.json written by
    # save_2 cover exactly this run. JEPSEN_TPU_JAX_PROFILE=1
    # (--jax-profile) additionally wraps the run in a jax.profiler
    # capture landing in the run dir.
    trace.fresh_run(test.get("name"))
    profile_cm = trace.jax_profile_session(
        store.test_dir(test) / "jax-profile")

    os_ = test["os"]
    db = test["db"]
    nemesis = test.get("nemesis")
    try:
        # L1: provision OS, then cycle the DB.
        control.on_nodes(test, os_.setup)
        try:
            with trace.span("db.cycle"):
                jdb.cycle(db, test)
            try:
                if nemesis is not None:
                    test["nemesis"] = nemesis = nemesis.setup(test)
                setup_clients(test)

                with profile_cm:
                    with relative_time(), trace.span("generator.run"):
                        history = interpreter.run(test)
                    test["history"] = jhistory.index(history)
                    store.save_1(test)

                    analyze(test)
            finally:
                try:
                    teardown_clients(test)
                finally:
                    if nemesis is not None:
                        try:
                            nemesis.teardown(test)
                        except Exception as e:
                            log.warning("nemesis teardown failed: %s", e)
        finally:
            snarf_logs(test)
            if not test.get("leave_db_running"):
                try:
                    jdb.teardown_all(db, test)
                except Exception as e:
                    log.warning("db teardown failed: %s", e)
    finally:
        try:
            control.on_nodes(test, os_.teardown)
        except Exception as e:
            log.warning("os teardown failed: %s", e)
    return test
