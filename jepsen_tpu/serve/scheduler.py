"""Tenant admission for the verdict daemon.

One `Admission` object owns every tenant's scheduling state: a
`parallel.folding.Lane` per (tenant, checker) pair, the per-tenant
queue-depth cap (JEPSEN_TPU_SERVE_MAX_QUEUE), and the weighted
deficit-round-robin fold selection (`parallel.folding.plan_fold`) the
daemon's dispatch loop pulls from. Admission control is priced by
PREDICTED WORK, not request count — the arxiv 1908.04509 posture: one
tenant's 5000-txn histories cost it ~1500x the fold share of another
tenant's 128-txn ones, so the queue-depth cap plus the cost-priced
fairness bound both dimensions a tenant can hog. The price is
`folding.fold_cost`'s T_pad² cell proxy by default; with
JEPSEN_TPU_PLANNER on, the daemon prices with the fitted cost model's
predicted device seconds normalized to the SAME cell unit
(`planner.admission_cost`), so budgets and the DRR below are
unchanged either way.

Backpressure is EXPLICIT: a full lane rejects the request and the
daemon answers a `retry-after` frame with a depth-derived delay hint —
a tenant is never silently dropped, and the admitted set is exactly
the journal-or-ack set.

Thread model: reader threads admit, the scheduler thread plans folds;
both go through the one condition variable here. Fold planning mutates
the lanes' deques — pure computation, done under the same condition so
no partially-planned fold is ever observable.
"""

from __future__ import annotations

import threading
import time

from .. import gates

#: Fold geometry: at most this many histories per fold, so one fold's
#: verdict latency stays bounded even when the queues are deep (the
#: cell budget bounds the big-history dimension; this bounds the
#: many-tiny-histories one).
DEFAULT_MAX_FOLD = 64


def parse_weights(spec: str | None = None) -> dict[str, float]:
    """`tenant=weight,...` from JEPSEN_TPU_SERVE_WEIGHTS (or an
    explicit spec). Malformed or non-positive entries are skipped —
    a bad weights string degrades to equal shares, never a crash."""
    if spec is None:
        spec = gates.get("JEPSEN_TPU_SERVE_WEIGHTS")
    out: dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, raw = part.partition("=")
        try:
            w = float(raw.strip())
        except ValueError:
            continue
        if w > 0:
            out[name.strip()] = w
    return out


def max_queue_depth() -> int:
    v = gates.get("JEPSEN_TPU_SERVE_MAX_QUEUE")
    return max(1, int(v)) if v is not None else 256


class Request:
    """One admitted (or about-to-be-admitted) check request."""

    __slots__ = ("tenant", "rid", "checker", "enc", "cost", "t0",
                 "conn")

    def __init__(self, tenant: str, rid: str, checker: str, enc,
                 cost: int, conn=None):
        self.tenant = tenant
        self.rid = rid
        self.checker = checker
        self.enc = enc          # encoding, or the encode Exception
        self.cost = cost
        self.t0 = time.perf_counter()
        self.conn = conn


class Admission:
    """The daemon's admission queue set (see module docstring)."""

    def __init__(self, weights: dict[str, float] | None = None,
                 max_queue: int | None = None):
        self._cv = threading.Condition()
        self._lanes: dict[tuple[str, str], object] = {}
        self._weights = dict(weights if weights is not None
                             else parse_weights())
        self._max_queue = max_queue if max_queue is not None \
            else max_queue_depth()
        self._pending = 0
        self._closed = False

    @property
    def max_queue(self) -> int:
        """This instance's per-tenant depth cap (the gate default, or
        the owner's explicit override)."""
        return self._max_queue

    # -- tenant registry ---------------------------------------------------

    def weight_of(self, tenant: str, requested=None) -> float:
        """The effective fairness weight: the operator's gate spec
        wins; a client-requested weight applies only for tenants the
        spec doesn't name (a tenant must not out-rank the operator)."""
        w = self._weights.get(tenant)
        if w is None and requested is not None:
            try:
                w = float(requested)
            except (TypeError, ValueError):
                w = None
        return max(float(w), 1e-3) if w and w > 0 else 1.0

    def _lane(self, tenant: str, checker: str, requested=None):
        from ..parallel import folding
        key = (tenant, checker)
        ln = self._lanes.get(key)
        if ln is None:
            ln = folding.Lane(tenant, self.weight_of(tenant, requested))
            self._lanes[key] = ln
        return ln

    def register(self, tenant: str, requested_weight=None) -> float:
        """Pre-create the tenant's append lane (hello time) and return
        the effective weight — the welcome frame reports it."""
        with self._cv:
            self._lane(tenant, "append", requested_weight)
        return self.weight_of(tenant, requested_weight)

    # -- admit / plan ------------------------------------------------------

    def depth(self, tenant: str) -> int:
        """The tenant's total queued histories across checkers."""
        with self._cv:
            return sum(len(ln.queue) for (t, _c), ln
                       in self._lanes.items() if t == tenant)

    def pending(self) -> int:
        with self._cv:
            return self._pending

    def close(self) -> None:
        """Close admission (drain): no request can enter a queue after
        this returns — the atomic half of the drain contract. A reader
        mid-encode that reaches `admit` after the scheduler observed
        an empty queue set is refused here, not admitted into a queue
        nobody will ever drain."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def admit(self, req: Request) -> bool:
        """Queue one request, or refuse it (False = backpressure: the
        tenant's lanes already hold max_queue histories — or admission
        is closed for drain)."""
        with self._cv:
            if self._closed:
                return False
            held = sum(len(ln.queue) for (t, _c), ln
                       in self._lanes.items() if t == req.tenant)
            if held >= self._max_queue:
                return False
            self._lane(req.tenant, req.checker).queue.append(req)
            self._pending += 1
            self._cv.notify_all()
        return True

    def retry_after_s(self) -> float:
        """The backpressure delay hint: proportional to the global
        backlog (a deep queue means a longer wait before capacity
        frees), floored so clients never busy-spin."""
        return round(min(30.0, max(0.2, 0.02 * self.pending())), 3)

    def wait_pending(self, timeout: float) -> bool:
        """Block until any request is queued (or timeout). The
        scheduler thread's park point."""
        with self._cv:
            if self._pending:
                return True
            self._cv.wait(timeout)
            return self._pending > 0

    def next_fold(self, budget_cells: int,
                  max_histories: int = DEFAULT_MAX_FOLD
                  ) -> tuple[str | None, list[Request]]:
        """The next shared bucket dispatch: picks the checker whose
        oldest queued request has waited longest (a fold is single-
        checker — append and wr ride different kernels), then runs the
        weighted DRR over that checker's lanes. Returns (checker,
        requests) — (None, []) when nothing is queued."""
        from ..parallel import folding
        with self._cv:
            oldest: tuple[float, str] | None = None
            for (_t, c), ln in self._lanes.items():
                if ln.queue:
                    t0 = ln.queue[0].t0
                    if oldest is None or t0 < oldest[0]:
                        oldest = (t0, c)
            if oldest is None:
                return None, []
            checker = oldest[1]
            lanes = [ln for (_t, c), ln in self._lanes.items()
                     if c == checker]
            picked = folding.plan_fold(lanes,
                                       budget_cells=budget_cells,
                                       max_histories=max_histories)
            self._pending -= len(picked)
            return checker, [req for _ln, req in picked]

    def tenants_snapshot(self) -> dict:
        """Per-tenant queue depths + weights for the health snapshot's
        serve section and the per-tenant gauges."""
        with self._cv:
            out: dict[str, dict] = {}
            for (t, _c), ln in self._lanes.items():
                d = out.setdefault(t, {"queued": 0,
                                       "weight": ln.weight})
                d["queued"] += len(ln.queue)
            return out
