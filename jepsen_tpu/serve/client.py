"""Tenant-side client for the verdict daemon.

A thin, dependency-light wrapper over the frame protocol: connect,
hello, stream CHECK frames, collect verdicts. Handles the service's
explicit flow control for the caller — `retry-after` frames are
honored by re-sending after the daemon's delay hint (bounded), so
`collect` returns exactly one verdict per submitted id or raises.

Retries are BOUNDED (the client half of the fleet failover contract):
backpressure resends and reconnects back off exponentially with
jitter, and once JEPSEN_TPU_SERVE_RETRY_S passes without progress (a
verdict landing, a connection succeeding) the client raises the
terminal `ServeUnavailable` instead of spinning forever against a
permanently dead endpoint. A router failover therefore shows up to a
tenant as at most a bounded stall, and a real outage as a clean error.

The bench's open-loop load generator, `make serve-smoke`/`fleet-smoke`
and the crash/restart tests all drive the REAL socket through this
class — there is no in-process shortcut to accidentally test instead.
"""

from __future__ import annotations

import random
import socket
import threading
import time

from .. import gates
from . import protocol


class ServeError(RuntimeError):
    pass


class ServeUnavailable(ServeError):
    """Terminal: the endpoint stayed unreachable or backpressured past
    JEPSEN_TPU_SERVE_RETRY_S without any progress. The caller's move
    is a fresh connection (possibly to a different endpoint), not
    another resend on this one."""


def retry_budget_s() -> float:
    """The JEPSEN_TPU_SERVE_RETRY_S no-progress budget (seconds; `0`
    fails on the first retryable condition)."""
    v = gates.get("JEPSEN_TPU_SERVE_RETRY_S")
    return max(0.0, float(v)) if v is not None else 60.0


class ServeClient:
    def __init__(self, socket_path=None, host: str = "127.0.0.1",
                 port: int | None = None, tenant: str = "default",
                 weight: float | None = None, timeout: float = 60.0):
        self.socket_path = socket_path
        self.host = host
        self.port = port
        self.tenant = tenant
        self.weight = weight
        self.timeout = timeout
        self.sock: socket.socket | None = None
        self.welcome: dict | None = None
        #: ids submitted but not yet verdicted (retry bookkeeping)
        self._inflight: dict[str, dict] = {}
        self.verdicts: dict[str, dict] = {}
        self.replays = 0
        self.retries = 0
        #: per-id submit/verdict monotonic stamps — the open-loop load
        #: generator's latency record (client-observed end to end)
        self.sent_at: dict[str, float] = {}
        self.done_at: dict[str, float] = {}
        # one connection may be driven by a submitter thread AND a
        # collector thread (the open-loop generator): frame sends are
        # serialized so two frames can't interleave on the stream
        self._slock = threading.Lock()

    # -- connection --------------------------------------------------------

    def _connect_once(self) -> dict:
        if self.port is not None:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.settimeout(self.timeout)
            s.connect((self.host, self.port))
        else:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self.timeout)
            s.connect(str(self.socket_path))
        self.sock = s
        hello = {"op": "hello", "tenant": self.tenant}
        if self.weight is not None:
            hello["weight"] = self.weight
        protocol.send_frame(s, hello)
        w = protocol.recv_frame(s)
        if not w or w.get("op") != "welcome":
            raise ServeError(f"expected welcome, got {w!r}")
        self.welcome = w
        return w

    def connect(self, retry: bool = False) -> dict:
        """Connect + hello. With `retry`, a refused/failed connect
        backs off exponentially (with jitter) and keeps trying until
        JEPSEN_TPU_SERVE_RETRY_S passes without success — then the
        terminal ServeUnavailable."""
        if not retry:
            return self._connect_once()
        budget = retry_budget_s()
        t0 = time.monotonic()
        attempt = 0
        while True:
            try:
                return self._connect_once()
            except (OSError, ServeError):
                if time.monotonic() - t0 > budget:
                    raise ServeUnavailable(
                        f"endpoint unreachable for {budget:.1f}s "
                        "(JEPSEN_TPU_SERVE_RETRY_S)") from None
                self._backoff_sleep(attempt)
                attempt += 1

    def close(self) -> None:
        if self.sock is not None:
            try:
                protocol.send_frame(self.sock, {"op": "bye"})
            except OSError:
                pass
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def __enter__(self) -> "ServeClient":
        self.connect()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission --------------------------------------------------------

    def _submit(self, frame: dict) -> str:
        rid = frame["id"]
        self._inflight[rid] = frame
        self.sent_at.setdefault(rid, time.monotonic())
        with self._slock:
            protocol.send_frame(self.sock, frame)
        return rid

    def check_dir(self, run_dir, checker: str = "append",
                  rid: str | None = None) -> str:
        """Submit a store run dir by reference (the daemon encodes it
        through the warm sidecar path — zero-copy on a v2 hit)."""
        return self._submit({"op": "check",
                             "id": rid or str(run_dir),
                             "checker": checker, "dir": str(run_dir)})

    def check_history(self, ops: list, rid: str,
                      checker: str = "append") -> str:
        """Submit inline history ops (the convenience path)."""
        return self._submit({"op": "check", "id": rid,
                             "checker": checker, "history": ops})

    def check_encoded(self, enc, rid: str,
                      checker: str = "append") -> str:
        """Submit a locally-encoded history through shared memory: the
        arrays are exported once into a segment and only the
        descriptor rides the socket — the daemon maps the same pages
        (zero-copy) and unlinks the name immediately."""
        from .. import shm
        payload = shm.export(enc, shm.gen_name(), checker)
        if shm.is_descriptor(payload):
            return self._submit({"op": "check", "id": rid,
                                 "checker": checker, "shm": payload})
        # shm unavailable: fall back to inline ops? The encoding has
        # no ops anymore — refuse loudly rather than silently re-parse
        raise ServeError("shared-memory export unavailable "
                         "(JEPSEN_TPU_SHM_INGEST=0 or /dev/shm "
                         "unusable); submit by dir or history instead")

    # -- collection --------------------------------------------------------

    def recv(self) -> dict | None:
        return protocol.recv_frame(self.sock)

    def _backoff_sleep(self, attempt: int, hint: float | None = None,
                       deadline: float | None = None) -> None:
        """Exponential backoff with jitter: the daemon's delay hint is
        the floor, doubling per attempt since last progress, capped —
        a thundering herd of retrying tenants decorrelates instead of
        hammering a recovering daemon in lockstep."""
        delay = min(5.0, max(float(hint or 0.0),
                             0.05 * (2 ** min(attempt, 7))))
        delay *= random.uniform(0.5, 1.0)
        if deadline is not None:
            delay = min(delay, max(0.0, deadline - time.monotonic()))
        time.sleep(delay)

    def _reconnect(self, last_progress: float, budget: float,
                   deadline: float | None) -> None:
        """Bounded reconnect for `collect(reconnect=True)`: back off
        until the endpoint answers (a restarted daemon, a router past
        its failover), then re-send every outstanding id — journaled
        verdicts replay, the rest re-check."""
        try:
            self.sock.close()
        except OSError:
            pass
        attempt = 0
        while True:
            if time.monotonic() - last_progress > budget:
                raise ServeUnavailable(
                    f"endpoint unreachable for {budget:.1f}s "
                    "(JEPSEN_TPU_SERVE_RETRY_S) with "
                    f"{len(self._inflight)} outstanding")
            if deadline is not None and time.monotonic() > deadline:
                raise ServeError(
                    f"collect timed out with {len(self._inflight)} "
                    "verdict(s) outstanding")
            self._backoff_sleep(attempt, deadline=deadline)
            attempt += 1
            try:
                self._connect_once()
                break
            except (OSError, ServeError):
                continue
        for pend in list(self._inflight.values()):
            with self._slock:
                protocol.send_frame(self.sock, pend)

    def collect(self, timeout: float | None = None,
                max_retries: int = 100,
                expect: int | None = None,
                reconnect: bool = False) -> dict[str, dict]:
        """Drain the socket until every submitted id has a verdict.
        `retry-after` frames re-submit after a jittered exponential
        backoff floored at the daemon's delay hint (up to
        `max_retries` total); a `draining` retry-after keeps retrying
        too — after a restart the new daemon replays from the journal.
        With `reconnect`, a closed connection is retried the same way
        (outstanding ids are re-sent after the new welcome) instead of
        raising. Either way, JEPSEN_TPU_SERVE_RETRY_S without progress
        is terminal: ServeUnavailable. With `expect`, keep collecting
        until that many TOTAL verdicts have landed — the open-loop
        generator's collector thread starts before the first
        submission, when the in-flight set is still empty. Returns
        {id: result}."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        budget = retry_budget_s()
        last_progress = time.monotonic()
        attempts = 0     # retryable conditions since last progress
        while self._inflight or (expect is not None
                                 and len(self.verdicts) < expect):
            if deadline is not None and time.monotonic() > deadline:
                raise ServeError(
                    f"collect timed out with {len(self._inflight)} "
                    f"verdict(s) outstanding")
            try:
                frame = self.recv()
            except OSError:
                frame = None
            if frame is None:
                if not reconnect:
                    raise ServeError(
                        "daemon closed the connection with "
                        f"{len(self._inflight)} outstanding")
                self._reconnect(last_progress, budget, deadline)
                attempts += 1
                continue
            op = frame.get("op")
            if op == "verdict":
                rid = frame.get("id")
                self._inflight.pop(rid, None)
                self.verdicts[rid] = frame["result"]
                self.done_at[rid] = time.monotonic()
                if frame.get("replay"):
                    self.replays += 1
                last_progress = time.monotonic()
                attempts = 0
            elif op == "retry-after":
                rid = frame.get("id")
                pend = self._inflight.get(rid)
                if pend is None:
                    continue
                if self.retries >= max_retries:
                    raise ServeError("retry budget exhausted")
                if time.monotonic() - last_progress > budget:
                    raise ServeUnavailable(
                        f"no progress in {budget:.1f}s "
                        "(JEPSEN_TPU_SERVE_RETRY_S) with "
                        f"{len(self._inflight)} outstanding")
                self.retries += 1
                self._backoff_sleep(
                    attempts, hint=float(frame.get("delay_s") or 0.2),
                    deadline=deadline)
                attempts += 1
                with self._slock:
                    protocol.send_frame(self.sock, pend)
            elif op == "error":
                raise ServeError(f"daemon error: {frame.get('error')}")
        return dict(self.verdicts)
