"""Tenant-side client for the verdict daemon.

A thin, dependency-light wrapper over the frame protocol: connect,
hello, stream CHECK frames, collect verdicts. Handles the service's
explicit flow control for the caller — `retry-after` frames are
honored by re-sending after the daemon's delay hint (bounded), so
`collect` returns exactly one verdict per submitted id or raises.

The bench's open-loop load generator, `make serve-smoke` and the
crash/restart tests all drive the REAL socket through this class —
there is no in-process shortcut to accidentally test instead.
"""

from __future__ import annotations

import socket
import threading
import time

from . import protocol


class ServeError(RuntimeError):
    pass


class ServeClient:
    def __init__(self, socket_path=None, host: str = "127.0.0.1",
                 port: int | None = None, tenant: str = "default",
                 weight: float | None = None, timeout: float = 60.0):
        self.socket_path = socket_path
        self.host = host
        self.port = port
        self.tenant = tenant
        self.weight = weight
        self.timeout = timeout
        self.sock: socket.socket | None = None
        self.welcome: dict | None = None
        #: ids submitted but not yet verdicted (retry bookkeeping)
        self._inflight: dict[str, dict] = {}
        self.verdicts: dict[str, dict] = {}
        self.replays = 0
        self.retries = 0
        #: per-id submit/verdict monotonic stamps — the open-loop load
        #: generator's latency record (client-observed end to end)
        self.sent_at: dict[str, float] = {}
        self.done_at: dict[str, float] = {}
        # one connection may be driven by a submitter thread AND a
        # collector thread (the open-loop generator): frame sends are
        # serialized so two frames can't interleave on the stream
        self._slock = threading.Lock()

    # -- connection --------------------------------------------------------

    def connect(self) -> dict:
        if self.port is not None:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.settimeout(self.timeout)
            s.connect((self.host, self.port))
        else:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self.timeout)
            s.connect(str(self.socket_path))
        self.sock = s
        hello = {"op": "hello", "tenant": self.tenant}
        if self.weight is not None:
            hello["weight"] = self.weight
        protocol.send_frame(s, hello)
        w = protocol.recv_frame(s)
        if not w or w.get("op") != "welcome":
            raise ServeError(f"expected welcome, got {w!r}")
        self.welcome = w
        return w

    def close(self) -> None:
        if self.sock is not None:
            try:
                protocol.send_frame(self.sock, {"op": "bye"})
            except OSError:
                pass
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def __enter__(self) -> "ServeClient":
        self.connect()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission --------------------------------------------------------

    def _submit(self, frame: dict) -> str:
        rid = frame["id"]
        self._inflight[rid] = frame
        self.sent_at.setdefault(rid, time.monotonic())
        with self._slock:
            protocol.send_frame(self.sock, frame)
        return rid

    def check_dir(self, run_dir, checker: str = "append",
                  rid: str | None = None) -> str:
        """Submit a store run dir by reference (the daemon encodes it
        through the warm sidecar path — zero-copy on a v2 hit)."""
        return self._submit({"op": "check",
                             "id": rid or str(run_dir),
                             "checker": checker, "dir": str(run_dir)})

    def check_history(self, ops: list, rid: str,
                      checker: str = "append") -> str:
        """Submit inline history ops (the convenience path)."""
        return self._submit({"op": "check", "id": rid,
                             "checker": checker, "history": ops})

    def check_encoded(self, enc, rid: str,
                      checker: str = "append") -> str:
        """Submit a locally-encoded history through shared memory: the
        arrays are exported once into a segment and only the
        descriptor rides the socket — the daemon maps the same pages
        (zero-copy) and unlinks the name immediately."""
        from .. import shm
        payload = shm.export(enc, shm.gen_name(), checker)
        if shm.is_descriptor(payload):
            return self._submit({"op": "check", "id": rid,
                                 "checker": checker, "shm": payload})
        # shm unavailable: fall back to inline ops? The encoding has
        # no ops anymore — refuse loudly rather than silently re-parse
        raise ServeError("shared-memory export unavailable "
                         "(JEPSEN_TPU_SHM_INGEST=0 or /dev/shm "
                         "unusable); submit by dir or history instead")

    # -- collection --------------------------------------------------------

    def recv(self) -> dict | None:
        return protocol.recv_frame(self.sock)

    def collect(self, timeout: float | None = None,
                max_retries: int = 100,
                expect: int | None = None) -> dict[str, dict]:
        """Drain the socket until every submitted id has a verdict.
        `retry-after` frames re-submit after the daemon's delay hint
        (up to `max_retries` total); a `draining` retry-after keeps
        retrying too — after a restart the new daemon replays from the
        journal. With `expect`, keep collecting until that many TOTAL
        verdicts have landed — the open-loop generator's collector
        thread starts before the first submission, when the in-flight
        set is still empty. Returns {id: result}."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while self._inflight or (expect is not None
                                 and len(self.verdicts) < expect):
            if deadline is not None and time.monotonic() > deadline:
                raise ServeError(
                    f"collect timed out with {len(self._inflight)} "
                    f"verdict(s) outstanding")
            frame = self.recv()
            if frame is None:
                raise ServeError("daemon closed the connection with "
                                 f"{len(self._inflight)} outstanding")
            op = frame.get("op")
            if op == "verdict":
                rid = frame.get("id")
                self._inflight.pop(rid, None)
                self.verdicts[rid] = frame["result"]
                self.done_at[rid] = time.monotonic()
                if frame.get("replay"):
                    self.replays += 1
            elif op == "retry-after":
                rid = frame.get("id")
                pend = self._inflight.get(rid)
                if pend is None:
                    continue
                if self.retries >= max_retries:
                    raise ServeError("retry budget exhausted")
                self.retries += 1
                time.sleep(min(float(frame.get("delay_s") or 0.2),
                               2.0))
                with self._slock:
                    protocol.send_frame(self.sock, pend)
            elif op == "error":
                raise ServeError(f"daemon error: {frame.get('error')}")
        return dict(self.verdicts)
