"""The verdict service's wire protocol: length-prefixed JSON frames.

Layout — one frame is::

    +-----------+----------------+---------------------+
    | b"JTSV"   | u32 big-endian | UTF-8 JSON object   |
    | (4 bytes) | payload length | (`length` bytes)    |
    +-----------+----------------+---------------------+

The magic makes a desynchronized stream fail LOUDLY (a reader that
lands mid-payload sees garbage where `JTSV` must be and raises,
instead of interpreting payload bytes as a length and hanging), and
the u32 bound caps a frame at 64 MiB — histories themselves never ride
the socket at that size: the zero-copy kinds carry descriptors.

Frame ops (the `"op"` key):

  client -> daemon
    hello       {tenant, weight?}           must be first
    check       {id, checker, dir|shm|history}
    adopt       {tenant}                    fleet failover: the router
                tells a successor daemon it now owns `tenant` — the
                daemon reloads that tenant's journal index from disk
                (another daemon may have appended since this one
                started) before any resent check lands, so journaled
                verdicts replay byte-identically instead of
                re-checking. In-order frame processing on the stream
                means the router can pipeline the resends right
                behind it; no reply frame.
    bye         {}                          polite close (EOF works too)

  daemon -> client
    welcome     {tenant, weight, journaled, max_queue}
    verdict     {id, checker, result, replay?}
    retry-after {id, delay_s, queue_depth, draining?}   backpressure —
                explicit, never a silent drop; resend after delay_s
    error       {error, id?}                protocol misuse

The fleet router speaks this same protocol on both sides: tenants
connect to it exactly as to a daemon, and it opens one upstream
connection per (tenant connection, daemon) replaying the hello. The
only router-era addition is `adopt` above.

A `check` names its history one of three ways:

  * `dir`     — a store run dir; the daemon encodes it through the
    warm ingest path (sidecar mmap, zero host copies on a v2 hit);
  * `shm`     — a `jepsen_tpu.shm` descriptor the TENANT exported; the
    daemon maps the same pages (and unlinks the name immediately, the
    transport's leak rule);
  * `history` — inline JSON ops (the convenience path; pays a full
    parse + encode in the daemon).

`id` is the tenant's stable name for the history — the journal key.
Re-sending an id the daemon already verdicted (same checker) replays
the journaled result without re-checking: at-least-once delivery with
idempotent checks.
"""

from __future__ import annotations

import json
import socket

MAGIC = b"JTSV"
MAX_FRAME = 64 << 20

#: The frame-kind registry — the wire protocol's single source of
#: truth. Every `op` either side may put on the wire, its direction
#: (`c2d` = client/tenant → daemon, `d2c` = daemon → client), and its
#: payload contract. The docstring table above is prose; THIS table
#: is what the JT-WIRE rules (lint/wireflow.py) prove the senders and
#: handlers in client.py/daemon.py/fleet.py against, and what the
#: README frame table is generated from (`make wire-table`). The
#: fleet router forwards both directions verbatim, so it carries no
#: handler obligations here — only its own emissions are checked.
FRAME_OPS: dict[str, dict] = {
    "hello": {
        "dir": "c2d",
        "required": ("tenant",),
        "optional": ("weight",),
        "doc": "must be the first frame on a connection"},
    "check": {
        "dir": "c2d",
        "required": ("id", "checker"),
        "optional": ("dir", "shm", "history"),
        "doc": "verdict request; names its history one of dir|shm|history"},
    "adopt": {
        "dir": "c2d",
        "required": ("tenant",),
        "optional": (),
        "doc": "failover: the successor daemon now owns the tenant"},
    "bye": {
        "dir": "c2d",
        "required": (),
        "optional": (),
        "doc": "polite close (EOF works too)"},
    "welcome": {
        "dir": "d2c",
        "required": ("tenant", "weight", "journaled", "max_queue"),
        "optional": (),
        "doc": "hello accepted"},
    "verdict": {
        "dir": "d2c",
        "required": ("id", "checker", "result"),
        "optional": ("replay", "stats", "journaled"),
        "doc": "checker result; replay=true on a journal hit"},
    "retry-after": {
        "dir": "d2c",
        "required": ("id", "delay_s", "queue_depth"),
        "optional": ("draining", "checker"),
        "doc": "backpressure — explicit, never a silent drop"},
    "error": {
        "dir": "d2c",
        "required": ("error",),
        "optional": ("id",),
        "doc": "protocol misuse; the connection usually survives"},
}


class ProtocolError(RuntimeError):
    """A malformed frame (bad magic, oversized length, junk JSON) —
    the stream is unrecoverable and the connection must close."""


def send_frame(sock: socket.socket, payload: dict) -> None:
    data = json.dumps(payload).encode()
    if len(data) > MAX_FRAME:
        raise ProtocolError(f"frame too large ({len(data)} bytes)")
    sock.sendall(MAGIC + len(data).to_bytes(4, "big") + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    """Exactly n bytes, or None on clean EOF at a frame boundary
    (zero bytes read). EOF mid-frame is a torn frame and raises."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise ProtocolError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> dict | None:
    """One frame, or None on clean EOF. Raises ProtocolError on a
    desynchronized/torn/oversized/junk frame."""
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    if header[:4] != MAGIC:
        raise ProtocolError(f"bad frame magic {header[:4]!r}")
    length = int.from_bytes(header[4:], "big")
    if length > MAX_FRAME:
        raise ProtocolError(f"frame length {length} exceeds cap")
    body = _recv_exact(sock, length) if length else b"{}"
    if body is None:
        raise ProtocolError("connection closed before frame body")
    try:
        payload = json.loads(body)
    except json.JSONDecodeError as e:
        raise ProtocolError(f"unparseable frame body: {e}") from e
    if not isinstance(payload, dict):
        raise ProtocolError("frame body is not a JSON object")
    return payload
