"""The serve fleet: N verdict daemons behind a fault-tolerant router.

`jepsen-tpu fleet` spawns (or attaches) N `serve` daemons — each a
normal `VerdictDaemon` in fleet mode: own socket `fleet-d<k>.sock`,
atomic beacon `fleet-d<k>.json` every JEPSEN_TPU_FLEET_HEARTBEAT_S,
the epoch fence — behind a thin router that tenants connect to at
`<store>/fleet.sock` speaking the unchanged JTSV frame protocol.

Routing: a tenant hash-affines to `live[shard_of(tenant, len(live))]`
(`store.shard_of`, the mesh's deterministic xxh64 partition), so a
tenant's checks land on one daemon's resident executables and its
replay index stays hot. When the affine daemon's load (beacon queue
depth + router-tracked in-flight, tie-broken on the beacon's
`hbm_modeled_bytes` from the PR-6 observability surfaces) crosses
JEPSEN_TPU_FLEET_SPILL_DEPTH, NEW checks spill to the least-loaded
live daemon instead of queueing deeper — measured load, not guesses.
Resends of an id the router already holds in flight stay sticky to
their daemon while it lives, so one id is queued on at most one
member at a time.

Death and failover: a member is declared dead on process exit,
connection failure, or beacon staleness past
JEPSEN_TPU_FLEET_FAILOVER_S — staleness is the KERNEL's file mtime,
never the daemon's self-reported wall clock, so a faketime-skewed
member is not falsely buried. Failover order is the fencing order:

  1. mark the member dead and bump the epoch in `fleet-epoch.json`
     (atomic replace) — the fence a resurrected zombie checks between
     a fold's compute and its journal writes;
  2. best-effort STONITH (SIGKILL the member's pid; `--no-stonith`
     for nemesis harnesses that own the process);
  3. for each tenant with in-flight work on the dead member: send
     `adopt {tenant}` to its successor (the daemon reloads the
     tenant's `serve-<t>.verdicts.jsonl` index FROM DISK), then
     pipeline the in-flight checks right behind it — journaled
     verdicts replay byte-identically, unjournaled ones re-check;
     one `fleet-reassign.jsonl` line records each move.

The invariant all of this serves: a tenant observes at most a bounded
retry-after across a daemon death — never a lost verdict (the journal
is always a superset of the acked set) and never a duplicated one
(the router forwards a verdict only while its id is in flight on that
member, and the epoch fence stops a zombie from journaling a
reassigned tenant's fold).

Caveat: `shm` submissions are single-daemon-lifetime (the daemon
unlinks the segment on map), so a fleet tenant that must survive
failover submits by `dir` or `history` — the warm zero-copy path for
dirs is the sidecar, which every member shares through the store.

Observability: the router owns the store's single health.json writer
(`fleet` section: epoch, per-member status/beacon age/load, tenant
assignments), serves `/metrics` (JEPSEN_TPU_METRICS_PORT) with
`fleet_*` counters/gauges and per-member `fleet.d<k>.*` gauges, and
emits `fleet_*` flight-recorder events; member daemons run with
health sampling and the metrics port off.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

from .. import gates, trace
from .. import store as store_mod
from ..obs import events as obs_events
from ..obs import health as obs_health
from ..obs import prom as obs_prom
from . import protocol

log = logging.getLogger(__name__)


def load_reassignments(store_base) -> list[dict]:
    """The `fleet-reassign.jsonl` reader: one dict per failover move,
    torn-tail tolerant like every journal reader (a router killed
    mid-append leaves a partial last line, skipped here and sealed by
    the next append)."""
    p = store_mod.fleet_reassign_path(store_base)
    out: list[dict] = []
    try:
        lines = p.read_text().splitlines()
    except OSError:
        return out
    for ln in lines:
        if not ln.strip():
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def heartbeat_s() -> float:
    v = gates.get("JEPSEN_TPU_FLEET_HEARTBEAT_S")
    return max(0.05, float(v)) if v is not None else 1.0


def failover_s() -> float:
    v = gates.get("JEPSEN_TPU_FLEET_FAILOVER_S")
    return max(0.1, float(v)) if v is not None else 5.0


def spill_depth() -> int:
    v = gates.get("JEPSEN_TPU_FLEET_SPILL_DEPTH")
    return max(1, int(v)) if v is not None else 32


class _Member:
    """One fleet daemon as the router sees it: spawned subprocess or
    attached (tests drive in-process daemons), beacon-backed."""

    def __init__(self, instance: int, socket_path, beacon_path,
                 proc=None, pid: int | None = None):
        self.instance = int(instance)
        self.socket_path = Path(socket_path)
        self.beacon_path = Path(beacon_path)
        self.proc = proc
        self.pid = pid
        self.status = "starting"      # starting -> live -> dead
        self.beacon: dict = {}
        self.beacon_age: float | None = None

    def current_pid(self) -> int | None:
        if self.proc is not None:
            return self.proc.pid
        if self.pid is not None:
            return self.pid
        p = self.beacon.get("pid")
        return int(p) if p else None


class _Upstream:
    """One router->daemon connection, per (tenant connection, member):
    the hello/welcome exchange happens synchronously at creation, then
    a pump thread forwards daemon->tenant frames."""

    def __init__(self, instance: int, sock: socket.socket,
                 welcome: dict):
        self.instance = instance
        self.sock = sock
        self.welcome = welcome
        self.alive = True
        self._wlock = threading.Lock()

    def send(self, payload: dict) -> bool:
        try:
            with self._wlock:
                protocol.send_frame(self.sock, payload)
            return True
        except (OSError, protocol.ProtocolError):
            self.alive = False
            return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class _TenantConn:
    """One tenant connection to the router. `inflight` maps
    (id, checker) -> {"frame", "member", "failover"?} — the router's
    resend evidence; an entry lives from the check forward to the
    verdict forward, and failover re-targets it to the successor."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.tenant: str | None = None
        self.hello: dict | None = None
        self.alive = True
        self.lock = threading.Lock()
        self.upstreams: dict[int, _Upstream] = {}
        self.inflight: dict[tuple[str, str], dict] = {}
        self._wlock = threading.Lock()

    def send(self, payload: dict) -> bool:
        try:
            with self._wlock:
                protocol.send_frame(self.sock, payload)
            return True
        except (OSError, protocol.ProtocolError):
            self.alive = False
            return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass
        with self.lock:
            ups = list(self.upstreams.values())
            self.upstreams.clear()
        for up in ups:
            up.close()


class FleetRouter:
    """See the module docstring. Lifecycle mirrors `VerdictDaemon`:
    `start()` spawns/attaches members and binds; `stop()` tears down.
    `spawn=False` + `attach_member(...)` lets tests drive in-process
    daemons (each still beaconing into the shared store)."""

    def __init__(self, store, daemons: int = 3, socket_path=None,
                 stonith: bool = True, spawn: bool = True,
                 member_env: dict[int, dict] | None = None,
                 start_timeout_s: float = 60.0):
        self.store = store
        self.daemons = int(daemons)
        self.socket_path = socket_path
        self.stonith = stonith
        self.spawn = spawn
        #: per-instance env additions for spawned members — the smoke's
        #: clock-skew fault preloads the faketime shim through this
        self.member_env = dict(member_env or {})
        self.start_timeout_s = start_timeout_s
        self._members: dict[int, _Member] = {}
        self._mlock = threading.Lock()
        self._epoch = 0
        self._conns: list[_TenantConn] = []
        self._cl = threading.Lock()
        self._suspects: set[int] = set()
        self._slock = threading.Lock()
        self._closing = threading.Event()
        self._listener: socket.socket | None = None
        self._sampler = None
        self._metrics = None
        self._threads: list[threading.Thread] = []
        self._verdicts = 0
        self._stopped = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetRouter":
        base = Path(self.store.base)
        base.mkdir(parents=True, exist_ok=True)
        trace.fresh_run(f"fleet:{base.name}", scope="sweep")
        from .. import obs
        obs.install_events(base)
        try:
            # per-sweep retention: this router's failover evidence
            # starts clean, like the daemon's request spool
            store_mod.fleet_reassign_path(base).unlink(missing_ok=True)
        except OSError:
            pass
        self._epoch = 1
        if self.spawn:
            for k in range(self.daemons):
                self._spawn_member(k)
        deadline = time.monotonic() + self.start_timeout_s
        for m in list(self._members.values()):
            if not self._wait_member_live(m, deadline):
                self.stop()
                raise RuntimeError(
                    f"fleet member d{m.instance} never beaconed "
                    f"(socket {m.socket_path})")
        self._write_epoch()
        self._bind()
        tr = trace.get_current()
        tr.gauge("fleet_daemons_live").set(len(self._live_members()))
        tr.gauge("fleet_epoch").set(self._epoch)
        # the router owns the store's ONE health.json writer; same
        # service default as the daemon (5 s unless the gate says)
        interval = obs_health.health_interval_s()
        if interval is None \
                and not gates.is_set("JEPSEN_TPU_HEALTH_INTERVAL_S"):
            interval = 5.0
        if interval:
            self._sampler = obs_health.HealthSampler(
                base, interval, extra_fn=self._fleet_section).start()
        self._metrics = obs_prom.maybe_start_metrics_server(
            health_fn=(self._sampler.write_snapshot
                       if self._sampler is not None else None))
        obs_events.emit("fleet_start", daemons=len(self._members),
                        socket=str(self._resolved_socket()),
                        epoch=self._epoch)
        acc = threading.Thread(target=self._accept_loop,
                               name="fleet-accept", daemon=True)
        acc.start()
        self._threads.append(acc)
        mon = threading.Thread(target=self._monitor_loop,
                               name="fleet-monitor", daemon=True)
        mon.start()
        self._threads.append(mon)
        log.info("fleet router serving %d daemon(s) on %s",
                 len(self._members), self._resolved_socket())
        return self

    def ready_info(self) -> dict:
        with self._mlock:
            members = {str(m.instance): {"socket": str(m.socket_path),
                                         "pid": m.current_pid(),
                                         "status": m.status}
                       for m in self._members.values()}
        return {"fleet": {
            "socket": str(self._resolved_socket()),
            "pid": os.getpid(),
            "epoch": self._epoch,
            "daemons": len(members),
            "members": members,
            "metrics_port": (self._metrics.port
                             if self._metrics is not None else None),
            "store": str(self.store.base)}}

    def stop(self) -> int:
        if self._stopped:
            return 0
        self._stopped = True
        self._closing.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._cl:
            conns = list(self._conns)
        for c in conns:
            c.close()
        obs_events.emit("fleet_stop", verdicts=self._verdicts,
                        daemons=len(self._live_members()))
        with self._mlock:
            members = list(self._members.values())
        for m in members:
            if m.proc is not None and m.proc.poll() is None:
                try:
                    m.proc.terminate()
                except OSError:
                    pass
        for m in members:
            if m.proc is not None:
                try:
                    m.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    try:
                        m.proc.kill()
                        m.proc.wait(timeout=5.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
        if self._sampler is not None:
            self._sampler.stop()
        if self._metrics is not None:
            self._metrics.stop()
        from .. import obs
        obs.reset_events()
        try:
            self._resolved_socket().unlink(missing_ok=True)
        except OSError:
            pass
        return 0

    # -- members -----------------------------------------------------------

    def attach_member(self, instance: int, socket_path,
                      pid: int | None = None) -> None:
        """Register an externally-managed member (in-process daemon or
        a subprocess the caller owns); call before `start()`. Pair
        with `stonith=False` when members share the caller's process:
        an in-process member's beacon carries the caller's pid, and a
        STONITH on conviction would SIGKILL the caller itself."""
        base = Path(self.store.base)
        with self._mlock:
            self._members[int(instance)] = _Member(
                instance, socket_path,
                store_mod.fleet_member_path(base, instance), pid=pid)

    def _spawn_member(self, k: int) -> None:
        base = Path(self.store.base)
        sock = store_mod.fleet_daemon_socket_path(base, k)
        env = dict(os.environ)
        # members must not fight the router (or each other) for the
        # metrics port, the store's health.json, or a serve socket
        # override meant for a standalone daemon
        for var in ("JEPSEN_TPU_METRICS_PORT",
                    "JEPSEN_TPU_HEALTH_INTERVAL_S",
                    "JEPSEN_TPU_SERVE_SOCKET",
                    "JEPSEN_TPU_SERVE_PORT"):
            env.pop(var, None)
        env.update({str(a): str(b) for a, b
                    in self.member_env.get(k, {}).items()})
        cmd = [sys.executable, "-m", "jepsen_tpu.cli", "serve",
               "--store", str(base), "--socket", str(sock),
               "--fleet-instance", str(k),
               "--fleet-epoch", str(self._epoch)]
        proc = subprocess.Popen(cmd, env=env,
                                stdout=subprocess.DEVNULL)
        with self._mlock:
            self._members[k] = _Member(
                k, sock, store_mod.fleet_member_path(base, k),
                proc=proc)

    def _wait_member_live(self, m: _Member, deadline: float) -> bool:
        while time.monotonic() < deadline:
            if m.proc is not None and m.proc.poll() is not None:
                return False
            if m.beacon_path.is_file() and m.socket_path.exists():
                try:
                    m.beacon = json.loads(m.beacon_path.read_text())
                except (OSError, json.JSONDecodeError):
                    time.sleep(0.05)
                    continue
                m.status = "live"
                obs_events.emit("fleet_daemon_up", instance=m.instance,
                                pid=m.current_pid())
                return True
            time.sleep(0.05)
        return False

    def _member(self, instance: int) -> _Member | None:
        with self._mlock:
            return self._members.get(instance)

    def _live_members(self) -> list[_Member]:
        with self._mlock:
            return sorted((m for m in self._members.values()
                           if m.status == "live"),
                          key=lambda m: m.instance)

    def _affine(self, tenant: str, live: list[_Member]) -> _Member:
        return live[store_mod.shard_of(tenant, len(live))]

    def _load(self, m: _Member) -> int:
        q = int(m.beacon.get("queue_depth") or 0)
        with self._cl:
            conns = list(self._conns)
        infl = 0
        for c in conns:
            with c.lock:
                infl += sum(1 for e in c.inflight.values()
                            if e["member"] == m.instance)
        return q + infl

    def _load_key(self, m: _Member) -> tuple:
        return (self._load(m),
                int(m.beacon.get("hbm_modeled_bytes") or 0),
                m.instance)

    # -- socket plumbing ---------------------------------------------------

    def _resolved_socket(self) -> Path:
        if self.socket_path:
            return Path(self.socket_path)
        return store_mod.fleet_socket_path(self.store.base)

    def _bind(self) -> None:
        path = self._resolved_socket()
        if path.exists():
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                probe.settimeout(1.0)
                probe.connect(str(path))
                raise RuntimeError(
                    f"a fleet router is already serving {path}")
            except (ConnectionRefusedError, socket.timeout,
                    FileNotFoundError, OSError):
                try:
                    path.unlink(missing_ok=True)
                except OSError:
                    pass
            finally:
                try:
                    probe.close()
                except OSError:
                    pass
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.bind(str(path))
        s.listen(128)
        self._listener = s

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            conn = _TenantConn(sock)
            with self._cl:
                self._conns.append(conn)
            t = threading.Thread(target=self._reader, args=(conn,),
                                 name="fleet-reader", daemon=True)
            t.start()

    # -- tenant side -------------------------------------------------------

    def _reader(self, conn: _TenantConn) -> None:
        try:
            while not self._closing.is_set():
                try:
                    frame = protocol.recv_frame(conn.sock)
                except protocol.ProtocolError as e:
                    conn.send({"op": "error", "error": str(e)[:300]})
                    return
                except OSError:
                    return
                if frame is None:
                    return
                op = frame.get("op")
                if op == "hello":
                    self._on_hello(conn, frame)
                elif op == "check":
                    self._route_check(conn, frame)
                elif op == "bye":
                    return
                else:
                    conn.send({"op": "error",
                               "error": f"unknown op {op!r}"})
        finally:
            conn.close()
            with self._cl:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _on_hello(self, conn: _TenantConn, frame: dict) -> None:
        conn.tenant = str(frame.get("tenant") or "") or "default"
        conn.hello = dict(frame)
        live = self._live_members()
        if not live:
            conn.send({"op": "error",
                       "error": "no live fleet members"})
            return
        up = self._upstream(conn, self._affine(conn.tenant, live))
        if up is None:
            conn.send({"op": "error",
                       "error": "fleet member unreachable; reconnect"})
            return
        conn.send(up.welcome)

    def _upstream(self, conn: _TenantConn,
                  m: _Member) -> _Upstream | None:
        with conn.lock:
            up = conn.upstreams.get(m.instance)
        if up is not None and up.alive:
            return up
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(10.0)
            s.connect(str(m.socket_path))
            hello = dict(conn.hello or {})
            hello.update({"op": "hello", "tenant": conn.tenant})
            protocol.send_frame(s, hello)
            w = protocol.recv_frame(s)
            if not w or w.get("op") != "welcome":
                raise protocol.ProtocolError(
                    f"expected welcome from d{m.instance}, got {w!r}")
            s.settimeout(None)
        except (OSError, protocol.ProtocolError):
            try:
                s.close()
            except OSError:
                pass
            self._suspect(m.instance)
            return None
        up = _Upstream(m.instance, s, w)
        with conn.lock:
            conn.upstreams[m.instance] = up
        t = threading.Thread(target=self._pump, args=(conn, up),
                             name=f"fleet-pump-d{m.instance}",
                             daemon=True)
        t.start()
        return up

    def _route_check(self, conn: _TenantConn, frame: dict) -> None:
        if conn.tenant is None:
            conn.send({"op": "error", "id": frame.get("id"),
                       "error": "hello must precede check"})
            return
        rid = str(frame.get("id") or "")
        checker = str(frame.get("checker") or "append")
        key = (rid, checker)
        live = self._live_members()
        if not live:
            # every member is down mid-failover: an explicit bounded
            # wait, never a silent drop — the client's RETRY_S budget
            # turns a permanent outage into ServeUnavailable
            conn.send({"op": "retry-after", "id": rid,
                       "delay_s": failover_s() / 2,
                       "queue_depth": 0, "draining": True})
            return
        target = None
        with conn.lock:
            ent = conn.inflight.get(key)
        if ent is not None:
            # sticky resend: one id queues on at most one member
            m = self._member(ent["member"])
            if m is not None and m.status == "live":
                target = m
        if target is None:
            target = affine = self._affine(conn.tenant, live)
            if len(live) > 1:
                depth = self._load(affine)
                if depth >= spill_depth():
                    best = min(live, key=self._load_key)
                    if best.instance != affine.instance:
                        target = best
                        trace.get_current().counter(
                            "fleet_spills").inc()
                        obs_events.emit("fleet_spill",
                                        tenant=conn.tenant,
                                        affine=affine.instance,
                                        chosen=best.instance,
                                        depth=depth)
        with conn.lock:
            conn.inflight[key] = {"frame": dict(frame),
                                  "member": target.instance}
        up = self._upstream(conn, target)
        if up is None or not up.send(frame):
            # the member died under this send: the inflight entry is
            # recorded, so the failover pass resends it
            self._suspect(target.instance)

    def _pump(self, conn: _TenantConn, up: _Upstream) -> None:
        while True:
            try:
                frame = protocol.recv_frame(up.sock)
            except (OSError, protocol.ProtocolError):
                frame = None
            if frame is None:
                up.alive = False
                if not self._closing.is_set() and conn.alive:
                    self._suspect(up.instance)
                return
            op = frame.get("op")
            if op in ("verdict", "retry-after"):
                key = (str(frame.get("id") or ""),
                       str(frame.get("checker") or "append"))
                if op == "retry-after" and not frame.get("checker"):
                    # retry-after frames carry no checker; match any
                    # in-flight entry with this id on this member
                    with conn.lock:
                        keys = [k for k, e in conn.inflight.items()
                                if k[0] == key[0]
                                and e["member"] == up.instance]
                    if not keys:
                        continue
                    conn.send(frame)
                    continue
                with conn.lock:
                    ent = conn.inflight.get(key)
                    if ent is None or ent["member"] != up.instance:
                        # late frame from a fenced zombie (or a
                        # duplicate after failover re-targeted the
                        # id): drop — the successor owns the reply
                        continue
                    if op == "verdict":
                        conn.inflight.pop(key, None)
                        replayed = bool(ent.get("failover")
                                        and frame.get("replay"))
                    else:
                        replayed = False
                if op == "verdict":
                    self._verdicts += 1
                    if replayed:
                        trace.get_current().counter(
                            "fleet_replayed_verdicts").inc()
                conn.send(frame)
            else:
                conn.send(frame)

    # -- death detection + failover ----------------------------------------

    def _suspect(self, instance: int) -> None:
        with self._slock:
            self._suspects.add(instance)

    def _monitor_loop(self) -> None:
        tick = min(0.25, heartbeat_s() / 2)
        while not self._closing.wait(tick):
            try:
                self._scan()
            except Exception:
                log.exception("fleet monitor scan failed")

    def _scan(self) -> None:
        fo = failover_s()
        with self._slock:
            suspects = set(self._suspects)
            self._suspects.clear()
        with self._mlock:
            members = list(self._members.values())
        tr = trace.get_current()
        for m in members:
            if m.status != "live":
                continue
            cause = None
            if m.proc is not None and m.proc.poll() is not None:
                cause = f"process exit {m.proc.returncode}"
            try:
                st = m.beacon_path.stat()
                m.beacon_age = max(0.0, time.time() - st.st_mtime)
                try:
                    m.beacon = json.loads(m.beacon_path.read_text())
                except (OSError, json.JSONDecodeError):
                    pass
            except OSError:
                # beacon retired: a clean drain (or a fenced zombie's
                # exit) — the member is gone either way
                m.beacon_age = None
                if cause is None:
                    cause = "beacon retired"
            if cause is None and m.beacon_age is not None \
                    and m.beacon_age > fo:
                # a SIGSTOPped member still accept()s (the kernel
                # backlog answers), so staleness alone is decisive
                cause = f"beacon stale {m.beacon_age:.1f}s"
            if cause is None and m.instance in suspects:
                if not self._probe(m):
                    cause = "connection refused"
            if cause is not None:
                self._fail_over(m, cause)
            else:
                tr.gauge(f"fleet.d{m.instance}.queue_depth").set(
                    int(m.beacon.get("queue_depth") or 0))

    def _probe(self, m: _Member) -> bool:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.settimeout(1.0)
            s.connect(str(m.socket_path))
            return True
        except OSError:
            return False
        finally:
            try:
                s.close()
            except OSError:
                pass

    def _fail_over(self, m: _Member, cause: str) -> None:
        with self._mlock:
            if m.status != "live":
                return
            m.status = "dead"
            self._epoch += 1
            epoch = self._epoch
        t0 = time.perf_counter()
        # 1. THE FENCE, before anything else: from here a resurrected
        # zombie drops its folds unjournaled instead of double-serving
        self._write_epoch()
        obs_events.emit("fleet_daemon_dead", instance=m.instance,
                        cause=cause, epoch=epoch)
        log.warning("fleet member d%d dead (%s); epoch -> %d",
                    m.instance, cause, epoch)
        # 2. best-effort STONITH: belt over the fence's suspenders
        if self.stonith:
            pid = m.current_pid()
            if pid:
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
        # 3. reassign + replay
        live = self._live_members()
        tr = trace.get_current()
        moved_tenants: list[str] = []
        with self._cl:
            conns = list(self._conns)
        for c in conns:
            if not c.alive or c.tenant is None:
                continue
            with c.lock:
                entries = [(k, e) for k, e in c.inflight.items()
                           if e["member"] == m.instance]
                dead_up = c.upstreams.pop(m.instance, None)
            if dead_up is not None:
                dead_up.close()
            if not entries:
                continue
            if not live:
                # nothing to fail over to: entries stay recorded; the
                # tenants' own resends route once a member returns
                continue
            succ = self._affine(c.tenant, live)
            up = self._upstream(c, succ)
            if up is None:
                continue
            # adopt-then-resend, pipelined: in-order processing on the
            # successor's stream guarantees the index reload lands
            # before the first resent check
            up.send({"op": "adopt", "tenant": c.tenant})
            moved = 0
            for k, e in entries:
                with c.lock:
                    e["member"] = succ.instance
                    e["failover"] = True
                if not up.send(e["frame"]):
                    self._suspect(succ.instance)
                    break
                moved += 1
            moved_tenants.append(c.tenant)
            self._append_reassign(epoch, m.instance, succ.instance,
                                  c.tenant, moved)
        dt_ms = (time.perf_counter() - t0) * 1000.0
        tr.counter("fleet_failovers").inc()
        tr.histogram("fleet_failover_ms").observe(dt_ms)
        tr.gauge("fleet_daemons_live").set(len(live))
        tr.gauge("fleet_epoch").set(epoch)
        obs_events.emit("fleet_failover", instance=m.instance,
                        successor=(live[0].instance if len(live) == 1
                                   else None),
                        tenants=len(moved_tenants), epoch=epoch,
                        ms=round(dt_ms, 3))

    # -- durable markers ---------------------------------------------------

    def _write_epoch(self) -> None:
        with self._mlock:
            data = {"epoch": self._epoch,
                    "router_pid": os.getpid(),
                    "t_wall": round(time.time(), 6),
                    "members": {str(m.instance):
                                {"status": m.status,
                                 "socket": str(m.socket_path)}
                                for m in self._members.values()}}
        try:
            trace.atomic_write_text(
                store_mod.fleet_epoch_path(self.store.base),
                json.dumps(data))
        except OSError:
            log.warning("epoch marker write failed", exc_info=True)

    def _append_reassign(self, epoch: int, dead: int, successor: int,
                         tenant: str, inflight: int) -> None:
        line = json.dumps({"epoch": epoch, "dead": dead,
                           "successor": successor, "tenant": tenant,
                           "inflight": inflight,
                           "t_wall": round(time.time(), 6)}) + "\n"
        try:
            with open(store_mod.fleet_reassign_path(self.store.base),
                      "a") as f:
                f.write(line)
                f.flush()
        except OSError:
            log.debug("reassign journal append failed", exc_info=True)

    # -- observability -----------------------------------------------------

    def _fleet_section(self) -> dict:
        with self._mlock:
            members = {}
            for m in self._members.values():
                members[str(m.instance)] = {
                    "status": m.status,
                    "pid": m.current_pid(),
                    "beacon_age_s": (round(m.beacon_age, 3)
                                     if m.beacon_age is not None
                                     else None),
                    "queue_depth": m.beacon.get("queue_depth"),
                    "hbm_modeled_bytes":
                        m.beacon.get("hbm_modeled_bytes"),
                }
        live = self._live_members()
        tenants = {}
        with self._cl:
            conns = list(self._conns)
        for c in conns:
            if c.tenant is None:
                continue
            with c.lock:
                on = sorted({e["member"]
                             for e in c.inflight.values()})
            tenants[c.tenant] = {
                "affine": (self._affine(c.tenant, live).instance
                           if live else None),
                "inflight_on": on}
        return {"fleet": {
            "epoch": self._epoch,
            "socket": str(self._resolved_socket()),
            "daemons": len(members),
            "live": len(live),
            "verdicts_forwarded": self._verdicts,
            "members": members,
            "tenants": tenants,
        }}


def run_fleet(store, daemons: int = 3, socket_path=None,
              stonith: bool = True) -> int:
    """The CLI body: start the router (spawning its daemons), print
    the machine-readable ready line, stop on SIGTERM/SIGINT."""
    router = FleetRouter(store, daemons=daemons,
                         socket_path=socket_path, stonith=stonith)
    try:
        router.start()
    except Exception:
        log.exception("fleet failed to start")
        router.stop()
        return 255
    done = threading.Event()

    def _on_signal(signum, _frame):
        done.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass
    print(json.dumps(router.ready_info()), flush=True)
    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    return router.stop()
