"""`make serve-smoke` — the verdict daemon's end-to-end acceptance.

Starts the REAL daemon (`python -m jepsen_tpu.cli serve`) as a
subprocess over a synthetic store, drives two concurrent tenants
through the real socket, scrapes `/metrics` while they stream,
SIGTERMs the daemon and asserts the full contract:

  * every streamed verdict is byte-identical (canonical JSON) to the
    post-hoc `analyze-store` verdict for the same history;
  * per-tenant series appear on `/metrics` and the `serve` section in
    health.json names both tenants;
  * SIGTERM drains cleanly (exit 0) with zero lost and zero
    duplicated journal entries — each tenant's journal holds exactly
    its submitted ids, once each;
  * the flight recorder carries the serve_* lifecycle.

Exit 0/1; every failure prints the failing contract.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

B, T, K, BAD_EVERY = 8, 128, 8, 4


def _child_env(store: Path) -> dict:
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "JEPSEN_TPU_PLATFORM": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "JEPSEN_TPU_METRICS_PORT": "0",
           "JEPSEN_TPU_HEALTH_INTERVAL_S": "0.5",
           "JEPSEN_TPU_SERVE_WEIGHTS": "fleetA=2,fleetB=1"}
    for k in ("JEPSEN_TPU_MESH", "JEPSEN_TPU_MESH_SHARD",
              "JEPSEN_TPU_MESH_SHARDS"):
        env.pop(k, None)
    return env


def _read_ready(proc, timeout: float = 180.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("daemon exited before ready line: "
                               + (proc.stderr.read() or "")[-400:])
        try:
            got = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(got, dict) and "serve" in got:
            return got["serve"]
    raise RuntimeError("timed out waiting for the daemon ready line")


def _scrape(port: int, path: str = "/metrics") -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.read().decode()


def _canon(v) -> str:
    return json.dumps(v, sort_keys=True)


def _journal_line_count(path: Path) -> int:
    """Raw line count of a journal file (duplicate detection: the
    deduplicating loader can't see a double-append)."""
    try:
        return sum(1 for ln in path.read_text().splitlines()
                   if ln.strip())
    except OSError:
        return -1


def main() -> int:
    from jepsen_tpu import obs
    from jepsen_tpu.checker.elle.synth import write_synth_store
    from jepsen_tpu.serve.client import ServeClient
    from jepsen_tpu.store import (Store, VerdictJournal,
                                  tenant_journal_path)

    failures: list[str] = []

    def check(ok: bool, what: str) -> None:
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    tmp = Path(tempfile.mkdtemp(prefix="serve-smoke-"))
    store = tmp / "store"
    (store / "synth").mkdir(parents=True)
    write_synth_store(store / "synth", B, T, K, BAD_EVERY)
    run_dirs = sorted(Store(store).iter_run_dirs())
    assert len(run_dirs) == B

    env = _child_env(store)
    proc = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.cli", "serve",
         "--store", str(store)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        ready = _read_ready(proc)
        check(ready.get("socket"), "daemon ready on a unix socket")
        mport = ready.get("metrics_port")
        check(bool(mport), "metrics endpoint up")

        halves = {"fleetA": run_dirs[: B // 2],
                  "fleetB": run_dirs[B // 2:]}
        results: dict[str, dict[str, dict]] = {}
        errs: list[str] = []

        def tenant_run(name: str, dirs) -> None:
            try:
                with ServeClient(socket_path=ready["socket"],
                                 tenant=name) as c:
                    for d in dirs:
                        c.check_dir(d)
                    results[name] = c.collect(timeout=300)
            except Exception as e:
                errs.append(f"{name}: {e!r}")

        threads = [threading.Thread(target=tenant_run, args=(n, ds))
                   for n, ds in halves.items()]
        for t in threads:
            t.start()

        # scrape while the tenants stream: loop until the serve series
        # (requests + a per-tenant series) appear, then keep the page
        page = ""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                page = _scrape(mport)
            except OSError:
                page = ""
            if "jepsen_tpu_serve_requests" in page \
                    and "jepsen_tpu_serve_fleetA_" in page:
                break
            time.sleep(0.2)
        check("jepsen_tpu_serve_requests" in page,
              "serve_requests counter on /metrics")
        check("jepsen_tpu_serve_fleetA_" in page
              and "jepsen_tpu_serve_fleetB_" in page,
              "per-tenant series on /metrics")

        for t in threads:
            t.join(timeout=300)
        check(not errs, f"both tenants collected ({errs})")
        check(all(len(results.get(n, {})) == len(ds)
                  for n, ds in halves.items()),
              "every submitted history got a verdict")

        # health.json serve section names both tenants
        health = {}
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                health = json.loads((store / "health.json").read_text())
            except (OSError, json.JSONDecodeError):
                health = {}
            ten = (health.get("serve") or {}).get("tenants") or {}
            if {"fleetA", "fleetB"} <= set(ten):
                break
            time.sleep(0.3)
        ten = (health.get("serve") or {}).get("tenants") or {}
        check({"fleetA", "fleetB"} <= set(ten),
              f"health.json serve section names both tenants ({ten})")

        # graceful drain
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            rc = -9
        check(rc == 0, f"SIGTERM drained cleanly (rc={rc})")

        # zero lost, zero duplicated journal entries
        for name, dirs in halves.items():
            p = tenant_journal_path(store, name)
            entries = VerdictJournal.load(p)
            want = {(str(d), "append") for d in dirs}
            check(set(entries) == want,
                  f"{name} journal holds exactly its ids "
                  f"({len(entries)}/{len(want)})")
            check(_journal_line_count(p) == len(want),
                  f"{name} journal has no duplicate lines")

        # serve_* lifecycle on the flight recorder
        kinds = {e.get("event") for e in obs.load_events(store)}
        check({"serve_start", "serve_tenant_connect", "serve_admit",
               "serve_drain", "serve_stop"} <= kinds,
              f"serve_* events recorded ({sorted(kinds)})")

        # byte-identical to the post-hoc batch path
        p2 = subprocess.run(
            [sys.executable, "-m", "jepsen_tpu.cli", "analyze-store",
             "--store", str(store)],
            cwd=REPO, env={k: v for k, v in env.items()
                           if k != "JEPSEN_TPU_METRICS_PORT"},
            capture_output=True, text=True, timeout=600)
        check(p2.returncode in (0, 1),
              f"analyze-store swept (rc={p2.returncode})")
        mismatches = []
        for name, dirs in halves.items():
            for d in dirs:
                streamed = results.get(name, {}).get(str(d))
                posthoc = json.loads((d / "results.json").read_text())
                if _canon(streamed) != _canon(posthoc):
                    mismatches.append(str(d))
        check(not mismatches,
              f"streamed verdicts byte-identical to analyze-store "
              f"({len(mismatches)} mismatch(es))")
        invalid = sum(1 for r in results.get("fleetA", {}).values()
                      if r.get("valid?") is False) \
            + sum(1 for r in results.get("fleetB", {}).values()
                  if r.get("valid?") is False)
        check(invalid == B // BAD_EVERY,
              f"invalid histories found ({invalid}/{B // BAD_EVERY})")
    finally:
        if proc.poll() is None:
            proc.kill()
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)

    if failures:
        print(f"serve-smoke: {len(failures)} contract(s) FAILED")
        return 1
    print("serve-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
