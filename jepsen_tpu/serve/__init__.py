"""jepsen_tpu.serve — the multi-tenant verdict daemon.

Everything before this package was post-hoc: a store is written, then
`analyze-store` sweeps it. At fleet scale (ROADMAP north star, open
item 2) the checker is a long-lived SERVICE: many concurrent test
fleets stream histories in over a local socket and get verdicts back
while their tests are still running — the online-checking posture of
arxiv 2504.01477, with admission control priced by history size per
the complexity bounds of arxiv 1908.04509. Four pieces:

  * `protocol` — the length-prefixed JSON frame layer (one magic+u32
    header per frame). CHECK frames carry a run-dir reference, a shm
    descriptor (`jepsen_tpu.shm`), or inline ops — the first two keep
    encode zero-copy end to end: the daemon mmaps the tenant's
    dispatch-shaped sidecar (or maps the tenant-exported segment)
    exactly the way the pooled sweep does.
  * `scheduler` — tenant admission: per-(tenant, checker) lanes with
    weighted-fairness (JEPSEN_TPU_SERVE_WEIGHTS) over
    `parallel.folding`'s deficit round-robin, a per-tenant queue-depth
    cap (JEPSEN_TPU_SERVE_MAX_QUEUE) answered with explicit
    `retry-after` frames — never a silent drop.
  * `daemon` — the `python -m jepsen_tpu.cli serve` process: holds
    AOT-cached executables and donated device slots resident
    (`parallel.residency`), continuously folds pending histories from
    different tenants into shared bucket dispatches as slots free up,
    journals every verdict to a per-tenant `serve-<t>.verdicts.jsonl`
    BEFORE acking it (a daemon crash loses nothing; reconnecting
    tenants replay from the journal without re-checking), drains
    gracefully on SIGTERM, and publishes `/metrics` + a `serve`
    section in health.json + `serve_*` flight-recorder events.
  * `client` — the tenant-side library the tests, the bench's open-
    loop load generator and `make serve-smoke` drive the real socket
    with. Retries are BOUNDED: exponential backoff with jitter, and a
    terminal `ServeUnavailable` once JEPSEN_TPU_SERVE_RETRY_S passes
    without progress — the client half of the failover contract.
  * `fleet` — `jepsen-tpu fleet`: N daemons (each `--fleet-instance
    k`, own socket + beacon) behind a thin frame-proxy router that
    hash-affines tenants via `store.shard_of`, spills to the least-
    loaded member on backpressure, declares a member dead on beacon
    staleness + connection failure, fences it out of the membership
    epoch, and replays its tenants' journals on a successor — zero
    lost or duplicated verdicts across a SIGKILL (`make fleet-smoke`
    proves it under a self-nemesis schedule).

`analyze-store` remains the batch path; the daemon is the streaming
one — both render verdicts through the same kernels and the same
renderers, so for the same history the two are byte-identical (the
`serve-smoke` acceptance check).
"""

from __future__ import annotations

from .daemon import VerdictDaemon, run_daemon  # noqa: F401
