"""The verdict daemon: `python -m jepsen_tpu.cli serve`.

One long-lived process per store. Reader threads (one per tenant
connection) admit CHECK frames into the `scheduler.Admission` lanes;
ONE dispatch thread continuously folds pending histories from
different tenants into shared bucket dispatches
(`parallel.folding.plan_fold` -> `FoldDispatcher`) as device slots
free up — compiled executables stay resident across folds
(`parallel.residency` + the PR-7 AOT cache), so a warm daemon pays
zero XLA compiles however long it runs.

Durability contract (the reason a daemon crash loses nothing):

  * every verdict is journaled to the tenant's
    `serve-<tenant>.verdicts.jsonl` (FULL result per line,
    `VerdictJournal` discipline) BEFORE the ack frame is sent —
    journal-then-reply, so the journal is always a superset of what
    any tenant saw;
  * a reconnecting tenant re-sends its ids and the daemon replays
    journaled verdicts from the index without re-checking (the PR-4
    journal-resume discipline, per tenant);
  * admitted requests additionally spool one line each to
    `serve-requests.jsonl` (cleared at daemon start) so a post-mortem
    can tell admitted-but-unverdicted work from never-admitted work.

Failure isolation: a fold that fails outright quarantines only its
own histories (`FoldDispatcher`); OOM backdown and the watchdog
degrade inside the fold exactly as in a sweep. The daemon itself only
exits on drain.

Observability: `/metrics` + `/healthz` (JEPSEN_TPU_METRICS_PORT) with
per-tenant `serve.<tenant>.*` series, a `serve` section in
`<store>/health.json` (sampled every 5 s by default for the daemon;
JEPSEN_TPU_HEALTH_INTERVAL_S overrides), `serve_*` flight-recorder
events, and a `serve_request` span per verdict on the trace fabric's
`serve` track.

Fleet mode (`fleet_instance` set — see `serve.fleet`): the daemon is
one member of a `jepsen-tpu fleet`. It binds `fleet-d<k>.sock`,
heartbeats an atomic `fleet-d<k>.json` beacon instead of `serve.pid`,
honors the router's `adopt` frames (reload a reassigned tenant's
journal index from disk), and checks the `fleet-epoch.json` fence
between a fold's compute and its journal writes — a zombie member
resurrected after the router fenced it drops the fold unjournaled
rather than double-serving a reassigned tenant.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from pathlib import Path

from .. import gates, trace
from .. import store as store_mod
from ..obs import events as obs_events
from ..obs import health as obs_health
from ..obs import prom as obs_prom
from . import protocol, scheduler

log = logging.getLogger(__name__)


def _json_safe(v):
    """The exact value canonicalization `cli._write_results` applies
    before persisting results.json — the daemon journals and acks the
    same bytes, which is what makes streamed verdicts byte-identical
    to the post-hoc sweep's."""
    from ..cli import _json_safe as impl
    return impl(v)


class RequestSpool:
    """The admitted-request spool: one flushed JSON line per admission
    (`{"tenant", "id", "checker"}`), cleared at daemon start — crash
    triage, not a replay source (the per-tenant journals own that)."""

    def __init__(self, store_base):
        self.path = store_mod.request_spool_path(store_base)
        self._f = None
        self._lock = threading.Lock()
        try:
            self.path.unlink(missing_ok=True)   # per-sweep retention
        except OSError:
            pass

    def append(self, tenant: str, rid: str, checker: str) -> None:
        line = json.dumps({"tenant": tenant, "id": rid,
                           "checker": checker,
                           "t_wall": round(time.time(), 6)}) + "\n"
        try:
            with self._lock:
                if self._f is None:
                    self._f = open(self.path, "a")
                self._f.write(line)
                self._f.flush()
        except (OSError, ValueError):
            log.debug("request spool append failed", exc_info=True)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    @staticmethod
    def load(path) -> list[dict]:
        """Spooled admissions in file order; unparseable lines (the
        crash-torn tail) are skipped, the journal reader's rule."""
        out: list[dict] = []
        p = Path(path)
        if not p.is_file():
            return out
        try:
            lines = p.read_text().splitlines()
        except OSError:
            return out
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                e = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if isinstance(e, dict) and "id" in e:
                out.append(e)
        return out


class _Conn:
    """One tenant connection; writes are serialized (the reader thread
    replays/backpressures and the dispatch thread acks verdicts on the
    same socket)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.tenant: str | None = None
        self.alive = True
        self._wlock = threading.Lock()

    def send(self, payload: dict) -> bool:
        try:
            with self._wlock:
                protocol.send_frame(self.sock, payload)
            return True
        except (OSError, protocol.ProtocolError):
            self.alive = False
            return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class VerdictDaemon:
    """See the module docstring. Lifecycle: `start()` binds and spins
    the threads; `run_until_drained()` blocks until a drain completes
    and tears everything down; `request_drain()` initiates one (the
    SIGTERM handler's body). In-process owners (bench, tests) call
    `start()` / `stop()`."""

    def __init__(self, store, socket_path=None, port: int | None = None,
                 host: str = "127.0.0.1",
                 budget_cells: int | None = None,
                 max_fold: int = scheduler.DEFAULT_MAX_FOLD,
                 weights: dict | None = None,
                 max_queue: int | None = None,
                 drain_s: float | None = None,
                 fleet_instance: int | None = None,
                 fleet_epoch: int | None = None):
        self.store = store
        self.socket_path = socket_path
        self.port = port
        self.host = host
        self.budget_cells = budget_cells
        self.max_fold = max_fold
        self.drain_s = drain_s
        #: fleet membership: set => this daemon is one member of a
        #: `jepsen-tpu fleet` (beacon heartbeats, epoch fence, adopt);
        #: None => the standalone PR-14 daemon, byte-for-byte unchanged
        self.fleet_instance = fleet_instance
        self.fleet_epoch = fleet_epoch if fleet_epoch is not None else 0
        self._fence_stat: tuple | None = None
        self._fence_data: dict = {}
        self.admission = scheduler.Admission(weights=weights,
                                             max_queue=max_queue)
        self._tenants: dict[str, dict] = {}
        self._jlock = threading.Lock()
        self._conns: list[_Conn] = []
        self._clock = threading.Lock()
        self._draining = threading.Event()
        self._closing = threading.Event()
        self._drain_deadline: float | None = None
        self._listener: socket.socket | None = None
        self._listen_desc: str | None = None
        self._spool: RequestSpool | None = None
        self._sampler = None
        self._metrics = None
        self._dispatcher = None
        self._threads: list[threading.Thread] = []
        self._sched_thread: threading.Thread | None = None
        self._stopped = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "VerdictDaemon":
        from .. import shm as _shm
        from ..parallel import folding
        base = Path(self.store.base)
        base.mkdir(parents=True, exist_ok=True)
        trace.fresh_run(f"serve:{base.name}", scope="sweep")
        tr = trace.get_current()
        tr.counter("shm_stale_reclaimed").inc(_shm.reclaim_stale())
        from .. import obs
        obs.install_events(base)
        if self.budget_cells is None:
            self.budget_cells = folding.DEFAULT_FOLD_CELLS
        self._dispatcher = folding.FoldDispatcher(
            budget_cells=self.budget_cells)
        # load the store's fitted dispatch plan (JEPSEN_TPU_PLANNER):
        # admission pricing then uses model-predicted cost instead of
        # the T_pad² proxy; gate off (or no plan.json yet) is a no-op
        from .. import planner as planner_mod
        planner_mod.activate(base)
        if self.fleet_instance is None:
            self._spool = RequestSpool(base)
        else:
            # fleet members share ONE store: a member starting must
            # not truncate the spool its peers are appending to. The
            # spool is crash triage, not replay — fleet triage reads
            # the router's reassignment journal instead.
            self._spool = None
        self._bind()
        if self.fleet_instance is None:
            trace.atomic_write_text(
                store_mod.serve_pid_path(base),
                json.dumps({"pid": os.getpid(),
                            "listen": self._listen_desc}))
        # the daemon is a service: health sampling defaults ON (5 s)
        # — an unset gate means "daemon default", an explicit <=0
        # disables, any other value overrides the interval. A FLEET
        # member defaults OFF: N daemons share one store, and the
        # router owns the single health.json writer (its `fleet`
        # section subsumes the per-daemon serve sections).
        interval = obs_health.health_interval_s()
        if interval is None \
                and not gates.is_set("JEPSEN_TPU_HEALTH_INTERVAL_S"):
            interval = 5.0 if self.fleet_instance is None else None
        if interval:
            self._sampler = obs_health.HealthSampler(
                base, interval, extra_fn=self._serve_section).start()
        self._metrics = obs_prom.maybe_start_metrics_server(
            health_fn=(self._sampler.write_snapshot
                       if self._sampler is not None else None))
        obs_events.emit("serve_start", listen=self._listen_desc,
                        store=str(base))
        if self.fleet_instance is not None:
            # first beacon synchronously (the router's spawn wait sees
            # the member the moment the ready line prints), then the
            # heartbeat thread keeps the kernel mtime fresh
            self._write_beacon(trace.get_current())
            bt = threading.Thread(target=self._beacon_loop,
                                  name="fleet-beacon", daemon=True)
            bt.start()
            self._threads.append(bt)
        acc = threading.Thread(target=self._accept_loop,
                               name="serve-accept", daemon=True)
        acc.start()
        self._threads.append(acc)
        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, name="serve-dispatch")
        self._sched_thread.start()
        log.info("verdict daemon serving on %s (store %s)",
                 self._listen_desc, base)
        return self

    def ready_info(self) -> dict:
        """The machine-readable ready line (`run_daemon` prints it)."""
        info = {
            "listen": self._listen_desc,
            "socket": (str(self._resolved_socket())
                       if self.port is None else None),
            "port": self.port,
            "pid": os.getpid(),
            "metrics_port": (self._metrics.port
                             if self._metrics is not None else None),
            "store": str(self.store.base)}
        if self.fleet_instance is not None:
            info["fleet_instance"] = self.fleet_instance
            info["fleet_epoch"] = self.fleet_epoch
        return {"serve": info}

    def request_drain(self, reason: str = "stop") -> None:
        """Close admission and let queued work finish (bounded by
        JEPSEN_TPU_SERVE_DRAIN_S). Idempotent; signal-handler-safe."""
        if self._draining.is_set():
            return
        drain_s = self.drain_s
        if drain_s is None:
            drain_s = gates.get("JEPSEN_TPU_SERVE_DRAIN_S")
        self._drain_deadline = time.monotonic() + max(0.0,
                                                      float(drain_s))
        # close admission BEFORE the draining flag becomes observable
        # (JT-ORD-005): the scheduler exits on draining ∧ pending==0,
        # so if the flag were set first a reader mid-encode could
        # still admit a request in the window before close() — one
        # the exiting scheduler would never serve. Closed-first,
        # admit() refuses it and the tenant gets the draining
        # retry-after instead.
        self.admission.close()
        self._draining.set()
        obs_events.emit("serve_drain", reason=reason,
                        pending=self.admission.pending())
        log.info("drain requested (%s): %d pending", reason,
                 self.admission.pending())

    def run_until_drained(self) -> int:
        """Block until the dispatch thread drains, then tear down.
        Returns the process exit code (0 = clean drain)."""
        try:
            while self._sched_thread.is_alive():
                self._sched_thread.join(timeout=0.5)
        except KeyboardInterrupt:
            self.request_drain("keyboard-interrupt")
            self._sched_thread.join()
        self._teardown()
        return 0

    def stop(self) -> int:
        """In-process owners' one-call exit: drain + wait + teardown."""
        self.request_drain("stop")
        return self.run_until_drained()

    def _teardown(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self._closing.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._clock:
            conns = list(self._conns)
        for c in conns:
            c.close()
        tr = trace.get_current()
        total = int(getattr(tr.counter("serve_verdicts"), "value", 0)
                    or 0)
        obs_events.emit("serve_stop", verdicts=total,
                        drained=self.admission.pending() == 0)
        with self._jlock:
            for ent in self._tenants.values():
                ent["journal"].close()
        if self._spool is not None:
            self._spool.close()
        if self._sampler is not None:
            self._sampler.stop()
        if self._metrics is not None:
            self._metrics.stop()
        from .. import obs
        obs.reset_events()
        base = Path(self.store.base)
        if self.fleet_instance is None:
            markers = (store_mod.serve_pid_path(base),)
        else:
            # a cleanly-exiting member retires its beacon; a SIGKILLed
            # one leaves it to go stale — the router's death evidence
            markers = (store_mod.fleet_member_path(
                base, self.fleet_instance),)
        for p in markers:
            try:
                p.unlink(missing_ok=True)
            except OSError:
                pass
        if self.port is None:
            try:
                self._resolved_socket().unlink(missing_ok=True)
            except OSError:
                pass

    # -- socket plumbing ---------------------------------------------------

    def _resolved_socket(self) -> Path:
        p = self.socket_path or gates.get("JEPSEN_TPU_SERVE_SOCKET")
        if p:
            return Path(p)
        if self.fleet_instance is not None:
            return store_mod.fleet_daemon_socket_path(
                self.store.base, self.fleet_instance)
        return store_mod.serve_socket_path(self.store.base)

    def _bind(self) -> None:
        if self.port is None:
            gate_port = gates.get("JEPSEN_TPU_SERVE_PORT")
            if gate_port is not None:
                self.port = gate_port
        if self.port is not None:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((self.host, self.port))
            s.listen(64)
            self.port = s.getsockname()[1]
            self._listen_desc = f"tcp://{self.host}:{self.port}"
        else:
            path = self._resolved_socket()
            if path.exists():
                # a live daemon answers a connect; a stale socket (the
                # previous daemon SIGKILLed) refuses — reclaim it
                probe = socket.socket(socket.AF_UNIX,
                                      socket.SOCK_STREAM)
                try:
                    probe.settimeout(1.0)
                    probe.connect(str(path))
                    probe.close()
                    raise RuntimeError(
                        f"a verdict daemon is already serving {path}")
                except (ConnectionRefusedError, socket.timeout,
                        FileNotFoundError, OSError):
                    try:
                        path.unlink(missing_ok=True)
                    except OSError:
                        pass
                finally:
                    try:
                        probe.close()
                    except OSError:
                        pass
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.bind(str(path))
            s.listen(64)
            self._listen_desc = f"unix://{path}"
        self._listener = s

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return   # listener closed: shutting down
            conn = _Conn(sock)
            with self._clock:
                self._conns.append(conn)
            t = threading.Thread(target=self._reader, args=(conn,),
                                 name="serve-reader", daemon=True)
            t.start()

    # -- per-connection reader ---------------------------------------------

    def _reader(self, conn: _Conn) -> None:
        try:
            while not self._closing.is_set():
                try:
                    frame = protocol.recv_frame(conn.sock)
                except protocol.ProtocolError as e:
                    conn.send({"op": "error", "error": str(e)[:300]})
                    return
                except OSError:
                    return
                if frame is None:
                    return
                op = frame.get("op")
                if op == "hello":
                    self._on_hello(conn, frame)
                elif op == "check":
                    self._on_check(conn, frame)
                elif op == "adopt":
                    self._on_adopt(conn, frame)
                elif op == "bye":
                    return
                else:
                    conn.send({"op": "error",
                               "error": f"unknown op {op!r}"})
        finally:
            conn.close()
            with self._clock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _tenant_state(self, tenant: str) -> dict:
        """The tenant's journal + replay index, created (and the
        journal's prior entries loaded — the resume evidence) on first
        contact after a (re)start."""
        with self._jlock:
            ent = self._tenants.get(tenant)
            if ent is None:
                p = store_mod.tenant_journal_path(self.store.base,
                                                  tenant)
                ent = {"journal": store_mod.VerdictJournal(p),
                       "index": store_mod.VerdictJournal.load(p),
                       "verdicts": 0}
                self._tenants[tenant] = ent
            return ent

    def _on_adopt(self, conn: _Conn, frame: dict) -> None:
        """Fleet failover: the router hands this daemon a dead peer's
        tenant. Reload the tenant's journal index FROM DISK — the dead
        peer appended verdicts after this daemon (maybe) first loaded
        it, and those must replay byte-identically, not re-check.
        In-order frame processing on this stream is the ordering
        guarantee: the router pipelines the resent checks right behind
        this frame, so no reply is needed."""
        tenant = str(frame.get("tenant") or "")
        if not tenant:
            conn.send({"op": "error", "error": "adopt names no tenant"})
            return
        p = store_mod.tenant_journal_path(self.store.base, tenant)
        idx = store_mod.VerdictJournal.load(p)
        with self._jlock:
            ent = self._tenants.get(tenant)
            if ent is None:
                self._tenants[tenant] = {
                    "journal": store_mod.VerdictJournal(p),
                    "index": idx, "verdicts": 0}
            else:
                # keep verdicts this daemon journaled itself that the
                # on-disk read may have raced past
                merged = dict(idx)
                merged.update(ent["index"])
                ent["index"] = merged

    def _on_hello(self, conn: _Conn, frame: dict) -> None:
        tenant = str(frame.get("tenant") or "") or "default"
        weight = self.admission.register(tenant, frame.get("weight"))
        conn.tenant = tenant
        ent = self._tenant_state(tenant)
        with self._jlock:
            journaled = len(ent["index"])
        tr = trace.get_current()
        tr.gauge("serve_tenants").set(len(self._tenants))
        obs_events.emit("serve_tenant_connect", tenant=tenant,
                        weight=weight, journaled=journaled)
        conn.send({"op": "welcome", "tenant": tenant,
                   "weight": weight, "journaled": journaled,
                   "max_queue": self.admission.max_queue})

    def _on_check(self, conn: _Conn, frame: dict) -> None:
        tr = trace.get_current()
        rid = str(frame.get("id") or "")
        if conn.tenant is None:
            conn.send({"op": "error", "id": rid,
                       "error": "hello must precede check"})
            return
        checker = str(frame.get("checker") or "append")
        if not rid or checker not in ("append", "wr"):
            conn.send({"op": "error", "id": rid,
                       "error": f"bad check frame (id={rid!r}, "
                                f"checker={checker!r})"})
            return
        tr.counter("serve_requests").inc()
        ent = self._tenant_state(conn.tenant)
        with self._jlock:
            prior = ent["index"].get((rid, checker))
        if prior is not None:
            # at-least-once delivery, idempotent checks: the journaled
            # verdict replays with zero device work
            res = prior.get("result")
            if res is None:
                res = {k: prior[k] for k in
                       ("valid?", "quarantined", "error")
                       if k in prior}
                res["checker"] = checker
            tr.counter("serve_replays").inc()
            conn.send({"op": "verdict", "id": rid, "checker": checker,
                       "result": res, "replay": True})
            return
        if self._draining.is_set():
            conn.send({"op": "retry-after", "id": rid,
                       "delay_s": self.admission.retry_after_s(),
                       "queue_depth": self.admission.depth(conn.tenant),
                       "draining": True})
            return
        # advisory load-shed BEFORE the encode: a tenant at its cap
        # must not make the daemon pay a full parse/encode per refused
        # retry (admit() below stays the atomic check)
        if self.admission.depth(conn.tenant) \
                >= self.admission.max_queue:
            self._send_backpressure(conn, rid, tr)
            return
        from .. import planner as planner_mod
        from ..parallel import folding
        enc = self._resolve_payload(frame, checker)
        n_txns = int(getattr(enc, "n", 1) or 1)
        pl = planner_mod.get()
        # admission price: the planner's model-predicted device
        # seconds normalized to fold_cost's cell unit when
        # JEPSEN_TPU_PLANNER is on (and fold_cost bit-exact on its
        # cold-start fallback); any positive cost preserves
        # plan_fold's weighted-DRR fairness semantics
        cost = (pl.admission_cost(n_txns, checker) if pl is not None
                else folding.fold_cost(n_txns))
        req = scheduler.Request(conn.tenant, rid, checker, enc, cost,
                                conn)
        if not self.admission.admit(req):
            if self._draining.is_set():
                # lost the race with a drain: admission closed while
                # this request was encoding — the draining frame, not
                # a backpressure count
                conn.send({"op": "retry-after", "id": rid,
                           "delay_s": self.admission.retry_after_s(),
                           "queue_depth":
                               self.admission.depth(conn.tenant),
                           "draining": True})
                return
            self._send_backpressure(conn, rid, tr)
            return
        if self._spool is not None:
            self._spool.append(conn.tenant, rid, checker)
        slug = store_mod.safe_tenant(conn.tenant)
        tr.gauge(f"serve.{slug}.queue_depth").set(
            self.admission.depth(conn.tenant))
        tr.gauge("serve_pending").set(self.admission.pending())

    def _send_backpressure(self, conn: _Conn, rid: str, tr) -> None:
        """The explicit refusal: counter + event + a retry-after frame
        with a backlog-derived delay hint — never a silent drop."""
        tr.counter("serve_backpressure").inc()
        depth = self.admission.depth(conn.tenant)
        obs_events.emit("serve_backpressure", tenant=conn.tenant,
                        depth=depth)
        conn.send({"op": "retry-after", "id": rid,
                   "delay_s": self.admission.retry_after_s(),
                   "queue_depth": depth})

    def _resolve_payload(self, frame: dict, checker: str):
        """CHECK frame -> encoding (or the Exception, which the fold
        quarantines at the `encode` stage — a tenant's bad history
        costs the tenant an `unknown` verdict, never the daemon)."""
        try:
            if frame.get("dir"):
                from .. import ingest
                with trace.span("serve_encode", kind="dir"):
                    return ingest.encode_run_dir(frame["dir"], checker)
            if frame.get("shm"):
                from .. import shm
                with trace.span("serve_encode", kind="shm"):
                    return shm.materialize(frame["shm"])
            if frame.get("history") is not None:
                with trace.span("serve_encode", kind="inline"):
                    if checker == "append":
                        from ..checker.elle.encode import (
                            encode_history, lean_anomalies)
                        enc = encode_history(frame["history"])
                        enc.anomalies = lean_anomalies(enc)
                    else:
                        from ..checker.elle.wr import (
                            encode_wr_history, lean_wr_anomalies)
                        enc = encode_wr_history(frame["history"])
                        enc.anomalies = lean_wr_anomalies(enc)
                enc.txn_ops = []
                return enc
            return ValueError(
                "check frame names no history (dir/shm/history)")
        except Exception as e:
            return e

    # -- the dispatch loop -------------------------------------------------

    def _scheduler_loop(self) -> None:
        tr = trace.get_current()
        while True:
            if self._draining.is_set():
                if self.admission.pending() == 0:
                    return
                if self._drain_deadline is not None \
                        and time.monotonic() > self._drain_deadline:
                    dropped = self.admission.pending()
                    log.warning("drain deadline passed with %d "
                                "unverdicted (tenants will resend)",
                                dropped)
                    return
            if not self.admission.wait_pending(0.2):
                continue
            checker, picked = self.admission.next_fold(
                self.budget_cells, self.max_fold)
            if not picked:
                continue
            try:
                self._run_fold(checker, picked, tr)
            except Exception:
                # _run_fold already quarantines per fold; anything
                # escaping here is a bug, but the daemon must not die
                log.exception("fold processing failed")

    def _run_fold(self, checker: str, picked: list, tr) -> None:
        from ..obs import search as obs_search
        by_tenant: dict[str, int] = {}
        for r in picked:
            by_tenant[r.tenant] = by_tenant.get(r.tenant, 0) + 1
        obs_events.emit("serve_admit", checker=checker,
                        histories=len(picked), tenants=by_tenant)
        # kernel search telemetry (JEPSEN_TPU_KERNEL_STATS): stats ride
        # the reply frame BESIDE "result" — the journaled/acked verdict
        # bytes stay identical with the gate on or off — and feed the
        # kernel.* metrics only (the daemon is long-lived; the
        # per-sweep ledger is analyze-store's)
        souts: list | None = [] if obs_search.enabled() else None
        with tr.span("serve_fold", checker=checker,
                     histories=len(picked),
                     tenants=len(by_tenant)):
            # the stats kwarg is passed only when requested, so
            # stats-free dispatcher doubles (test seams) keep working
            if souts is not None:
                results = self._dispatcher.verdicts(
                    [r.enc for r in picked], checker,
                    stats_out=souts)
            else:
                results = self._dispatcher.verdicts(
                    [r.enc for r in picked], checker)
        tr.counter("serve_folds").inc()
        tr.histogram("serve_fold_histories").observe(len(picked))
        if self._fenced():
            # the zombie fence: this member was declared dead and its
            # tenants reassigned while the fold ran (SIGSTOP-resume,
            # partition heal). Journaling now would DUPLICATE lines the
            # successor is already writing for the same ids — drop the
            # whole fold unjournaled and unacked (the router already
            # replayed/re-checked these on the successor) and drain.
            tr.counter("fleet_fences").inc()
            obs_events.emit("fleet_fence", instance=self.fleet_instance,
                            epoch=self._fence_data.get("epoch"),
                            histories=len(picked))
            log.warning("fenced at epoch %s: dropping a %d-history "
                        "fold unjournaled and draining",
                        self._fence_data.get("epoch"), len(picked))
            self.request_drain("fenced")
            return
        for k, (r, res) in enumerate(zip(picked, results)):
            stats = souts[k] if souts is not None \
                and k < len(souts) else None
            if stats is not None:
                obs_search.note_metrics(stats, tr)
            res = _json_safe(res)
            ent = self._tenant_state(r.tenant)
            with self._jlock:
                # journal-then-reply: the ack below can only name a
                # verdict the journal already holds — unless the
                # append itself failed (read-only/full store), which
                # is surfaced on the frame: that verdict will be
                # RE-CHECKED after a restart, not replayed
                journaled = ent["journal"].record(r.rid, checker, res,
                                                  full=True)
                ent["index"][(r.rid, checker)] = {
                    "dir": r.rid, "checker": checker,
                    "valid?": res.get("valid?"), "result": res}
                ent["verdicts"] += 1
            if not journaled:
                log.warning("journal append failed for tenant %s id "
                            "%s — ack sent unjournaled (will "
                            "re-check after a restart)",
                            r.tenant, r.rid)
            # metrics before the ack: the moment a tenant sees its
            # verdict, the counters already account for it (a scrape
            # can lag an ack, never undercount a completed set)
            now = time.perf_counter()
            tr.histogram("serve_latency_ms").observe(
                (now - r.t0) * 1000.0)
            tr.counter("serve_verdicts").inc()
            slug = store_mod.safe_tenant(r.tenant)
            tr.counter(f"serve.{slug}.verdicts").inc()
            tr.add_span("serve_request", r.t0, now, track="serve",
                        tenant=r.tenant, id=r.rid, checker=checker)
            if r.conn is not None and r.conn.alive:
                frame = {"op": "verdict", "id": r.rid,
                         "checker": checker, "result": res}
                if stats is not None:
                    frame["stats"] = stats
                if not journaled:
                    frame["journaled"] = False
                r.conn.send(frame)
        for t in by_tenant:
            slug = store_mod.safe_tenant(t)
            tr.gauge(f"serve.{slug}.queue_depth").set(
                self.admission.depth(t))
        tr.gauge("serve_pending").set(self.admission.pending())

    # -- fleet membership --------------------------------------------------

    def _fenced(self) -> bool:
        """Is this member marked dead in the epoch marker? Checked
        between a fold's compute and its journal writes — the last
        possible moment a resurrected zombie can be stopped before it
        double-serves a reassigned tenant. The marker re-parses only
        on an mtime/size change (one stat per fold otherwise)."""
        if self.fleet_instance is None:
            return False
        p = store_mod.fleet_epoch_path(self.store.base)
        try:
            st = p.stat()
        except OSError:
            return False
        key = (st.st_mtime_ns, st.st_size)
        if key != self._fence_stat:
            try:
                data = json.loads(p.read_text())
            except (OSError, json.JSONDecodeError):
                # a marker mid-replace reads clean or not at all
                # (atomic_write_text), but stay conservative
                return False
            self._fence_stat = key
            self._fence_data = data if isinstance(data, dict) else {}
        # alien shapes (members as a list, an entry as a bare string —
        # e.g. a hand-edited or version-skewed marker) must degrade to
        # "not fenced", never crash the fold loop mid-verdict
        m = self._fence_data.get("members")
        ent = m.get(str(self.fleet_instance)) if isinstance(m, dict) \
            else None
        return bool(isinstance(ent, dict)
                    and ent.get("status") == "dead")

    def _write_beacon(self, tr, seq: int = 0) -> None:
        """One atomic beacon rewrite. The router reads LIVENESS off
        the file's kernel-set mtime (a faketime-skewed member cannot
        lie about its own freshness) and LOAD off the payload."""
        try:
            hbm = int(getattr(tr.gauge("hbm_modeled_bytes"), "value",
                              0) or 0)
        except Exception:
            hbm = 0
        beacon = {"instance": self.fleet_instance,
                  "pid": os.getpid(),
                  "epoch": self.fleet_epoch,
                  "listen": self._listen_desc,
                  "seq": seq,
                  "queue_depth": self.admission.pending(),
                  "hbm_modeled_bytes": hbm,
                  "draining": self._draining.is_set(),
                  "t_wall": round(time.time(), 6)}
        try:
            trace.atomic_write_text(
                store_mod.fleet_member_path(self.store.base,
                                            self.fleet_instance),
                json.dumps(beacon))
        except OSError:
            log.debug("beacon write failed", exc_info=True)

    def _beacon_loop(self) -> None:
        tr = trace.get_current()
        seq = 1
        while not self._closing.is_set():
            interval = gates.get("JEPSEN_TPU_FLEET_HEARTBEAT_S")
            self._closing.wait(max(0.05, float(interval or 1.0)))
            if self._closing.is_set():
                return
            self._write_beacon(tr, seq)
            seq += 1

    # -- observability -----------------------------------------------------

    def _serve_section(self) -> dict:
        """The health.json `serve` section (rides the sampler's
        extra_fn seam)."""
        with self._jlock:
            verdicts = {t: ent["verdicts"]
                        for t, ent in self._tenants.items()}
        tenants = {}
        for t, d in self.admission.tenants_snapshot().items():
            tenants[t] = {**d, "verdicts": verdicts.get(t, 0)}
        for t, n in verdicts.items():
            tenants.setdefault(t, {"queued": 0, "weight": 1.0,
                                   "verdicts": n})
        return {"serve": {
            "listen": self._listen_desc,
            "pid": os.getpid(),
            "draining": self._draining.is_set(),
            "pending": self.admission.pending(),
            "tenants": tenants,
        }}


def run_daemon(store, socket_path=None, port: int | None = None,
               host: str = "127.0.0.1",
               drain_s: float | None = None,
               fleet_instance: int | None = None,
               fleet_epoch: int | None = None) -> int:
    """The CLI body: start the daemon, print the machine-readable
    ready line, drain on SIGTERM/SIGINT, exit 0 on a clean drain."""
    import signal
    import sys

    d = VerdictDaemon(store, socket_path=socket_path, port=port,
                      host=host, drain_s=drain_s,
                      fleet_instance=fleet_instance,
                      fleet_epoch=fleet_epoch)
    d.start()

    def _on_signal(signum, _frame):
        d.request_drain(f"signal {signum}")

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass   # not the main thread / unsupported platform
    print(json.dumps(d.ready_info()), flush=True)
    try:
        return d.run_until_drained()
    except Exception:
        log.exception("verdict daemon crashed")
        try:
            d._teardown()
        except Exception:
            pass
        print("verdict daemon crashed", file=sys.stderr)
        return 255
