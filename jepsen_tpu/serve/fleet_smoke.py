"""`make fleet-smoke` — the serve fleet's acceptance under self-nemesis.

Starts a REAL 3-daemon fleet (the router in-process, each member a
real `python -m jepsen_tpu.cli serve --fleet-instance k` subprocess)
over a synthetic store and drives three tenants through the router
socket while a nemesis schedule — built from the `jepsen_tpu.nemesis`
combinators (`Nemesis` + `compose`, targets drawn with `split_one`)
— breaks members underneath them:

  * socket partition: the member's unix socket path is renamed aside
    and healed; established streams keep flowing, the beacon stays
    fresh, and the router must NOT bury the member (epoch unchanged);
  * SIGKILL mid-load (the acceptance fault): the affine member of one
    tenant is killed with checks in flight — the router fences the
    epoch, adopts the tenant on a successor, and replays/re-checks;
  * SIGSTOP (hammer): a stopped member still accept()s from the
    kernel backlog, so only beacon STALENESS can convict it — the
    router must declare it dead within JEPSEN_TPU_FLEET_FAILOVER_S
    and STONITH it;
  * clock skew: one member runs under the `native/faketime_shim.cc`
    LD_PRELOAD (built best-effort; the fault is skipped without a
    compiler) with its REALTIME clock an hour ahead and 25 % fast —
    beacon liveness is kernel mtime, so the skewed member must
    survive the whole schedule.

The contract asserted at the end is the fleet invariant:

  * every tenant lands every verdict across both deaths — zero lost;
  * each tenant's journal holds exactly its submitted ids, ONCE each
    (raw line count, so a zombie double-append can't hide behind the
    deduplicating loader) — zero duplicated;
  * a full resubmit of every id after both failovers replays
    byte-identically from the journals (client `replays` > 0);
  * streamed verdicts are byte-identical (canonical JSON) to a
    post-hoc single-process `analyze-store` sweep of the same store;
  * `fleet_*` lifecycle events, `fleet_*` /metrics series, the
    `fleet` section in health.json, and >=1 `fleet-reassign.jsonl`
    line all record what happened.

Exit 0/1; every failure prints the failing contract.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

B, T, K, BAD_EVERY = 12, 96, 8, 4
TENANTS = ("fleetA", "fleetB", "fleetC")
SKEW_INSTANCE = 2
SKEW_OFFSET_S, SKEW_RATE = 3600.0, 1.25


def _setup_env() -> None:
    os.environ.update({
        "JAX_PLATFORMS": "cpu",
        "JEPSEN_TPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "JEPSEN_TPU_METRICS_PORT": "0",
        "JEPSEN_TPU_HEALTH_INTERVAL_S": "0.5",
        # fast heartbeats so both failovers land inside the smoke's
        # budget; the client's retry budget comfortably covers them
        "JEPSEN_TPU_FLEET_HEARTBEAT_S": "0.25",
        "JEPSEN_TPU_FLEET_FAILOVER_S": "2.0",
        "JEPSEN_TPU_SERVE_RETRY_S": "120",
    })
    for k in ("JEPSEN_TPU_MESH", "JEPSEN_TPU_MESH_SHARD",
              "JEPSEN_TPU_MESH_SHARDS", "JEPSEN_TPU_SERVE_SOCKET",
              "JEPSEN_TPU_SERVE_PORT"):
        os.environ.pop(k, None)


def _build_shim(tmp: Path) -> Path | None:
    """Best-effort local build of the faketime LD_PRELOAD shim (the
    node-side recipe from `jepsen_tpu.faketime`, run here)."""
    src = REPO / "native" / "faketime_shim.cc"
    so = tmp / "libfaketime_shim.so"
    try:
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-pthread",
             "-o", str(so), str(src), "-ldl"],
            check=True, capture_output=True, timeout=180)
        return so
    except (OSError, subprocess.SubprocessError):
        return None


def _scrape(port: int, path: str = "/metrics") -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.read().decode()


def _canon(v) -> str:
    return json.dumps(v, sort_keys=True)


def _journal_line_count(path: Path) -> int:
    """Raw line count (duplicate detection: the deduplicating loader
    can't see a double-append)."""
    try:
        return sum(1 for ln in path.read_text().splitlines()
                   if ln.strip())
    except OSError:
        return -1


def main() -> int:  # noqa: C901 - a linear acceptance script
    _setup_env()

    from jepsen_tpu import nemesis, obs
    from jepsen_tpu.checker.elle.synth import write_synth_store
    from jepsen_tpu.serve import fleet as fleet_mod
    from jepsen_tpu.serve.client import ServeClient
    from jepsen_tpu.store import (Store, VerdictJournal,
                                  tenant_journal_path)

    failures: list[str] = []

    def check(ok: bool, what: str) -> None:
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    # -- the self-nemesis: local faults through the combinator layer --
    class ProcSignalNemesis(nemesis.Nemesis):
        """SIGKILL / SIGSTOP / SIGCONT a fleet member by pid."""
        fs = frozenset({"kill", "pause", "resume"})
        SIGS = {"kill": signal.SIGKILL, "pause": signal.SIGSTOP,
                "resume": signal.SIGCONT}

        def invoke(self, test, op):
            try:
                os.kill(int(op["value"]), self.SIGS[op["f"]])
            except ProcessLookupError:
                return {**op, "type": "info", "value": "gone"}
            return {**op, "type": "info"}

    class SocketPartitionNemesis(nemesis.Nemesis):
        """Partition a member's socket from NEW connections by moving
        the path aside; established streams keep flowing."""
        fs = frozenset({"partition", "heal"})

        def invoke(self, test, op):
            p = Path(op["value"])
            if op["f"] == "partition":
                p.rename(p.with_suffix(".partitioned"))
            else:
                p.with_suffix(".partitioned").rename(p)
            return {**op, "type": "info"}

    nem = nemesis.compose([ProcSignalNemesis(),
                           SocketPartitionNemesis()])
    test: dict = {"nodes": []}
    nem.setup(test)

    tmp = Path(tempfile.mkdtemp(prefix="fleet-smoke-"))
    store = tmp / "store"
    (store / "synth").mkdir(parents=True)
    write_synth_store(store / "synth", B, T, K, BAD_EVERY)
    run_dirs = sorted(Store(store).iter_run_dirs())
    assert len(run_dirs) == B
    per = B // len(TENANTS)
    dirs = {t: run_dirs[i * per:(i + 1) * per]
            for i, t in enumerate(TENANTS)}

    shim = _build_shim(tmp)
    member_env = {}
    if shim is not None:
        member_env[SKEW_INSTANCE] = {
            "LD_PRELOAD": str(shim),
            "JEPSEN_FAKETIME_OFFSET_S": str(SKEW_OFFSET_S),
            "JEPSEN_FAKETIME_RATE": str(SKEW_RATE)}
        print(f"ok   clock-skew fault armed on d{SKEW_INSTANCE} "
              f"(+{SKEW_OFFSET_S:.0f}s, x{SKEW_RATE})")
    else:
        print("SKIP clock-skew fault (no compiler for the shim)")

    router = fleet_mod.FleetRouter(Store(store), daemons=3,
                                   member_env=member_env)
    clients: dict[str, ServeClient] = {}
    want: dict[str, dict[str, dict]] = {t: {} for t in TENANTS}
    try:
        router.start()
        ready = router.ready_info()["fleet"]
        check(ready["daemons"] == 3 and ready["epoch"] == 1,
              f"3-daemon fleet up at epoch 1 ({ready['daemons']}, "
              f"epoch {ready['epoch']})")
        mport = ready.get("metrics_port")
        check(bool(mport), "router metrics endpoint up")

        for t in TENANTS:
            c = ServeClient(socket_path=ready["socket"], tenant=t)
            c.connect(retry=True)
            clients[t] = c

        # -- wave 1: a clean half-load on the healthy fleet ----------
        for t in TENANTS:
            for d in dirs[t][: per // 2]:
                clients[t].check_dir(d)
        for t in TENANTS:
            got = clients[t].collect(timeout=300, reconnect=True)
            want[t].update(got)
        check(all(len(want[t]) == per // 2 for t in TENANTS),
              "wave 1: every tenant landed its verdicts")

        page = ""
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                page = _scrape(mport)
            except OSError:
                page = ""
            if "jepsen_tpu_fleet_daemons_live" in page:
                break
            time.sleep(0.2)
        check("jepsen_tpu_fleet_daemons_live" in page,
              "fleet gauges on the router /metrics")

        # -- fault 1: socket partition, healed — NOT a death ---------
        live = router._live_members()
        loner = nemesis.split_one([m.instance for m in live])[0][0]
        sock = router._member(loner).socket_path
        nem.invoke(test, {"f": "partition", "value": str(sock)})
        time.sleep(1.0)       # several monitor scans with it severed
        nem.invoke(test, {"f": "heal", "value": str(sock)})
        time.sleep(0.5)
        check(router._member(loner).status == "live"
              and router._epoch == 1,
              f"partitioned d{loner} not buried while its beacon "
              f"stayed fresh (epoch {router._epoch})")

        # -- fault 2: SIGKILL the affine member of fleetA MID-LOAD ---
        for t in TENANTS:
            for d in dirs[t][per // 2:]:
                clients[t].check_dir(d)
        kill_m = router._affine(TENANTS[0], router._live_members())
        nem.invoke(test, {"f": "kill", "value": kill_m.current_pid()})
        for t in TENANTS:
            got = clients[t].collect(timeout=300, reconnect=True)
            want[t].update(got)
        check(all(len(want[t]) == per for t in TENANTS),
              f"SIGKILL d{kill_m.instance} mid-load: every tenant "
              "still landed every verdict")
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline \
                and router._member(kill_m.instance).status != "dead":
            time.sleep(0.1)
        check(router._member(kill_m.instance).status == "dead"
              and router._epoch == 2,
              f"router convicted d{kill_m.instance} and fenced "
              f"epoch -> {router._epoch}")

        # -- fault 3: SIGSTOP another member (beacon staleness) ------
        live = [m for m in router._live_members()]
        hammer = next((m for m in live
                       if m.instance != SKEW_INSTANCE), live[0])
        nem.invoke(test, {"f": "pause",
                          "value": hammer.current_pid()})
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and router._member(hammer.instance).status != "dead":
            time.sleep(0.1)
        check(router._member(hammer.instance).status == "dead"
              and router._epoch == 3,
              f"SIGSTOPped d{hammer.instance} convicted on beacon "
              f"staleness (epoch {router._epoch})")
        nem.invoke(test, {"f": "resume",
                          "value": hammer.current_pid()})

        if SKEW_INSTANCE in member_env \
                and SKEW_INSTANCE not in (kill_m.instance,
                                          hammer.instance):
            check(router._member(SKEW_INSTANCE).status == "live",
                  f"clock-skewed d{SKEW_INSTANCE} never falsely "
                  "buried (liveness is kernel mtime)")

        # -- wave 3: full resubmit replays from the journals ---------
        for t in TENANTS:
            for d in dirs[t]:
                clients[t].check_dir(d)
        replays_ok, byte_ok = True, True
        for t in TENANTS:
            got = clients[t].collect(timeout=300, reconnect=True)
            replays_ok &= clients[t].replays > 0
            for d in dirs[t]:
                if _canon(got.get(str(d))) != _canon(
                        want[t].get(str(d))):
                    byte_ok = False
        check(replays_ok, "post-failover resubmits replayed from "
                          "the journals")
        check(byte_ok, "replayed verdicts byte-identical to the "
                       "originals")

        # -- observability surfaces ----------------------------------
        try:
            page = _scrape(mport)
        except OSError:
            page = ""
        # (fleet_replayed_verdicts only materializes when a failover
        # resend hits an already-journaled id — a race the schedule
        # doesn't pin down — so only the guaranteed series are asserted)
        check("jepsen_tpu_fleet_failovers" in page
              and "jepsen_tpu_fleet_epoch" in page,
              "failover + epoch series on /metrics")

        health = {}
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                health = json.loads(
                    (store / "health.json").read_text())
            except (OSError, json.JSONDecodeError):
                health = {}
            if (health.get("fleet") or {}).get("epoch") == 3:
                break
            time.sleep(0.3)
        fl = health.get("fleet") or {}
        dead = sorted(k for k, m in (fl.get("members") or {}).items()
                      if m.get("status") == "dead")
        check(fl.get("epoch") == 3 and len(dead) == 2,
              f"health.json fleet section: epoch {fl.get('epoch')}, "
              f"dead members {dead}")

        reassigns = fleet_mod.load_reassignments(store)
        check(len(reassigns) >= 1
              and all(r["dead"] != r["successor"]
                      for r in reassigns),
              f"fleet-reassign.jsonl records the moves "
              f"({len(reassigns)} line(s))")

        for c in clients.values():
            c.close()
        clients.clear()
        rc = router.stop()
        check(rc == 0, f"router stopped cleanly (rc={rc})")

        # -- the invariant: zero lost, zero duplicated ---------------
        for t in TENANTS:
            p = tenant_journal_path(store, t)
            entries = VerdictJournal.load(p)
            ids = {(str(d), "append") for d in dirs[t]}
            check(set(entries) == ids,
                  f"{t} journal holds exactly its ids "
                  f"({len(entries)}/{len(ids)})")
            check(_journal_line_count(p) == len(ids),
                  f"{t} journal has no duplicate lines across "
                  "the failovers")

        kinds = {e.get("event") for e in obs.load_events(store)}
        need = {"fleet_start", "fleet_daemon_up", "fleet_daemon_dead",
                "fleet_failover", "fleet_stop"}
        check(need <= kinds,
              f"fleet_* lifecycle events recorded ({sorted(kinds & need)})")

        # -- byte parity with the post-hoc batch path ----------------
        env = {k: v for k, v in os.environ.items()
               if k != "JEPSEN_TPU_METRICS_PORT"}
        p2 = subprocess.run(
            [sys.executable, "-m", "jepsen_tpu.cli", "analyze-store",
             "--store", str(store)],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=600)
        check(p2.returncode in (0, 1),
              f"analyze-store swept (rc={p2.returncode})")
        mismatches = [str(d) for t in TENANTS for d in dirs[t]
                      if _canon(want[t].get(str(d))) != _canon(
                          json.loads((d / "results.json").read_text()))]
        check(not mismatches,
              f"fleet verdicts byte-identical to analyze-store "
              f"({len(mismatches)} mismatch(es))")
        invalid = sum(1 for t in TENANTS
                      for r in want[t].values()
                      if r.get("valid?") is False)
        check(invalid == B // BAD_EVERY,
              f"invalid histories found ({invalid}/{B // BAD_EVERY})")
    finally:
        for c in clients.values():
            try:
                c.close()
            except Exception:
                pass
        router.stop()
        nem.teardown(test)
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)

    if failures:
        print(f"fleet-smoke: {len(failures)} contract(s) FAILED")
        return 1
    print("fleet-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
