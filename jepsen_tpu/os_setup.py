"""L1: operating-system provisioning on DB nodes.

Counterpart of jepsen.os + jepsen.os.debian
(jepsen/src/jepsen/os.clj:4-8, os/debian.clj:149-184): prepares a node —
package installs, hostfile entries, network healing — before the DB lands
on it.
"""

from __future__ import annotations

import logging

from . import control

log = logging.getLogger(__name__)


class OS:
    def setup(self, test: dict, node: str) -> None:
        pass

    def teardown(self, test: dict, node: str) -> None:
        pass


class NoopOS(OS):
    pass


def noop() -> OS:
    return NoopOS()


DEBIAN_PACKAGES = (
    # The toolbox the fault layer and daemon helpers rely on
    # (os/debian.clj:149-184).
    "curl", "wget", "unzip", "iptables", "iputils-ping", "iproute2",
    "logrotate", "man-db", "net-tools", "ntpdate", "psmisc", "rsyslog",
    "tar", "vim", "gcc", "libc6-dev", "tcpdump",
)


class DebianOS(OS):
    """apt-based setup: install the support toolbox, write /etc/hosts
    entries for the cluster, heal any leftover partitions."""

    def __init__(self, extra_packages: tuple = ()):
        self.packages = DEBIAN_PACKAGES + tuple(extra_packages)

    def _install(self, sess) -> None:
        """Install the toolbox, retrying once after a cache refresh."""
        sess.exec(control.Lit(
            "DEBIAN_FRONTEND=noninteractive apt-get install -y -q "
            + " ".join(self.packages)
            + " || (apt-get update && DEBIAN_FRONTEND=noninteractive "
              "apt-get install -y -q " + " ".join(self.packages) + ")"))

    def setup(self, test, node):
        sess = control.current_session().su()
        log.info("%s setting up %s", node, type(self).__name__)
        self._install(sess)
        self._setup_hostfile(sess, test)
        # Heal leftover partitions from crashed prior runs.
        sess.exec_ok("iptables", "-F", "-w")
        sess.exec_ok("iptables", "-X", "-w")

    def _setup_hostfile(self, sess, test):
        nodes = test.get("nodes", [])
        if not nodes:
            return
        from .control import net as cnet
        lines = ["127.0.0.1 localhost"]
        for n in nodes:
            lines.append(f"{cnet.ip(sess, n)} {n}")
        hosts = "\\n".join(lines)
        sess.exec(control.Lit(
            f"printf '%b\\n' \"{hosts}\" > /etc/hosts"))

    def teardown(self, test, node):
        pass


def debian(extra_packages: tuple = ()) -> OS:
    return DebianOS(extra_packages)


CENTOS_PACKAGES = (
    # os/centos.clj's toolbox (same roles as the debian list).
    "curl", "wget", "unzip", "iptables", "iputils", "iproute",
    "logrotate", "man-db", "net-tools", "ntpdate", "psmisc", "rsyslog",
    "tar", "vim", "gcc", "glibc-devel", "tcpdump",
)


class CentOS(DebianOS):
    """yum-based setup (jepsen/src/jepsen/os/centos.clj): same toolbox
    and hostfile/heal steps as Debian, different package manager."""

    def __init__(self, extra_packages: tuple = ()):
        self.packages = CENTOS_PACKAGES + tuple(extra_packages)

    def _install(self, sess) -> None:
        sess.exec(control.Lit(
            "yum install -y -q " + " ".join(self.packages)
            + " || (yum makecache -y -q && yum install -y -q "
            + " ".join(self.packages) + ")"))


def centos(extra_packages: tuple = ()) -> OS:
    return CentOS(extra_packages)


class UbuntuOS(DebianOS):
    """Ubuntu is Debian with the same apt toolbox
    (jepsen/src/jepsen/os/ubuntu.clj wraps debian's installer)."""


def ubuntu(extra_packages: tuple = ()) -> OS:
    return UbuntuOS(extra_packages)


SMARTOS_PACKAGES = ("curl", "wget", "unzip", "gtar", "gcc", "vim")


class SmartOS(OS):
    """pkgin-based setup (jepsen/src/jepsen/os/smartos.clj): minimal
    toolbox; no iptables (SmartOS uses ipfilter, see net.ipfilter)."""

    def __init__(self, extra_packages: tuple = ()):
        self.packages = SMARTOS_PACKAGES + tuple(extra_packages)

    def setup(self, test, node):
        sess = control.current_session().su()
        log.info("%s setting up smartos", node)
        sess.exec(control.Lit(
            "pkgin -y install " + " ".join(self.packages)))


def smartos(extra_packages: tuple = ()) -> OS:
    return SmartOS(extra_packages)
