"""Device discovery for the analysis data plane.

The default JAX backend wins (a real TPU slice when present), but:
  * JEPSEN_TPU_PLATFORM=cpu|tpu|... pins a platform explicitly (tests pin
    cpu so the 8-device virtual host mesh is used even on machines where
    a TPU plugin registers itself regardless of JAX_PLATFORMS),
  * a minimum device count can be requested — if the preferred backend is
    smaller, we fall back to the host-platform devices, which honors
    --xla_force_host_platform_device_count virtual meshes, and
  * initialization of an UNPINNED default backend is guarded by a
    bounded subprocess probe: a TPU plugin whose transport is down can
    hang `jax.devices()` indefinitely (it did, for 9+ minutes, in the
    round-2 bench), and a benchmark/checker must degrade to CPU with a
    structured error instead of hanging. The probe runs once per
    process and is memoized.
"""

from __future__ import annotations

import os
import subprocess
import sys

from . import gates

# Result of the one-shot default-backend probe: None = not yet run,
# (True, None) = healthy, (False, "err...") = dead/unreachable.
_probe_result: tuple[bool, str | None] | None = None

# Platform the successful probe reported (e.g. "tpu", "cpu"); None
# until a probe succeeds. Lets `auto` resolution answer "is there an
# accelerator?" without ever importing jax in this process.
_probe_platform: str | None = None

# Why the last default_devices() call fell back to CPU (None if it
# didn't). Benchmarks surface this in their structured output.
backend_error: str | None = None


class BackendUnavailable(RuntimeError):
    """The default JAX backend failed its bounded health probe.

    Raised instead of attempting any in-process fallback: once a dead
    device plugin's sitecustomize hook has registered itself, even
    `jax.devices("cpu")` after a config re-pin can initialize the dead
    backend and wedge forever (observed >90s in round 3). The only safe
    CPU fallback is a FRESH process with JAX_PLATFORMS=cpu in the env
    before jax import — which is what the bench supervisor and the
    jax-free CPU oracles provide."""


def probe_timeout() -> float:
    return gates.get("JEPSEN_TPU_PROBE_TIMEOUT")


def _backends_already_alive() -> bool:
    """True when this process already initialized JAX backends — probing
    again would be pure waste (and the hang risk is already behind us)."""
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:
        return False


def probe_default_backend(timeout: float | None = None) -> tuple[bool, str | None]:
    """Initialize the default JAX backend in a THROWAWAY subprocess with a
    wall-clock bound. Returns (ok, error). Memoized per process.

    This is the only safe way to ask "is the TPU tunnel alive?": doing it
    in-process risks wedging the caller forever, because backend init
    holds the lock `jax.devices()` needs and a dead transport never
    returns."""
    global _probe_result, _probe_platform
    if _probe_result is not None:
        return _probe_result
    if _backends_already_alive():
        try:
            import jax
            _probe_platform = jax.devices()[0].platform
        except Exception:
            pass
        _probe_result = (True, None)
        return _probe_result
    timeout = probe_timeout() if timeout is None else timeout
    code = ("import jax; d = jax.devices(); "
            "print('JEPSEN_PROBE_OK', len(d), d[0].platform)")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
        if p.returncode == 0 and "JEPSEN_PROBE_OK" in p.stdout:
            for line in p.stdout.splitlines():
                if line.startswith("JEPSEN_PROBE_OK"):
                    parts = line.split()
                    if len(parts) >= 3:
                        _probe_platform = parts[2]
            _probe_result = (True, None)
        else:
            tail = (p.stderr or p.stdout).strip().splitlines()[-1:]
            _probe_result = (False, f"backend init failed (rc={p.returncode}): "
                                    f"{' '.join(tail)[:300]}")
    except subprocess.TimeoutExpired:
        _probe_result = (False, f"backend init hung > {timeout:.0f}s "
                                "(transport down?); falling back to cpu")
    except Exception as e:  # probe infrastructure itself failed
        _probe_result = (False, f"probe error: {e!r}"[:300])
    return _probe_result


def _pin_platform(want: str) -> None:
    """Best-effort re-pin of jax_platforms after a plugin (e.g. a TPU
    tunnel) force-updated the config from sitecustomize, overriding the
    JAX_PLATFORMS env var. NOT a hang guarantee: some plugin hooks
    initialize their backend regardless of this config (observed in
    round 3 — a post-pin `jax.devices("cpu")` still wedged >90s on a
    dead tunnel). Only a fresh process with JAX_PLATFORMS=cpu set
    before jax import is truly safe; code that must not hang should
    avoid jax entirely (see resolve_backend) or run in an env-pinned
    subprocess (see bench.py's supervisor)."""
    import jax
    if jax.config.jax_platforms != want:
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass


def _requested_platform() -> str | None:
    plat = gates.get("JEPSEN_TPU_PLATFORM")
    want = plat or os.environ.get("JAX_PLATFORMS")
    if want and "axon" not in want:
        _pin_platform(want)
    return plat


def ensure_platform_pin() -> None:
    """Re-assert the JEPSEN_TPU_PLATFORM/JAX_PLATFORMS env pin on the
    jax config. Kernel modules call this at import: plugins that
    force-update jax_platforms from sitecustomize otherwise win over
    the user's env var, and the first jit of ANY entry point would
    initialize the plugin backend (hanging the process when its
    transport is down). Cheap — a config write, no backend init."""
    _requested_platform()


def _cpu_only_pin() -> bool:
    """True when the env pins an explicitly CPU-only platform set —
    the one case where probing is pure waste. A pin that *mentions* a
    device transport (e.g. the axon plugin exporting
    JAX_PLATFORMS=axon,cpu) still needs the bounded probe: its
    transport may be down, and in-process init would wedge."""
    want = gates.get("JEPSEN_TPU_PLATFORM") \
        or os.environ.get("JAX_PLATFORMS")
    if not want:
        return False
    return {p.strip() for p in want.split(",") if p.strip()} <= {"cpu"}


def default_devices(min_count: int = 1, *, probe: bool = False) -> list:
    """The analysis devices. With probe=True (benchmarks, explicit
    device entry points), a backend whose platform set isn't CPU-only
    is first health-checked in a bounded subprocess; on failure we
    raise BackendUnavailable with the reason in `devices.backend_error`
    — we do NOT attempt an in-process CPU fallback, because a dead
    plugin's hook can wedge even `jax.devices("cpu")` (round-3
    finding). Callers degrade via a fresh env-pinned process or the
    jax-free CPU oracles."""
    global backend_error
    if probe and not _backends_already_alive() and not _cpu_only_pin():
        ok, err = probe_default_backend()
        if not ok:
            backend_error = err
            raise BackendUnavailable(err)
    import jax

    plat = _requested_platform()
    if plat:
        return jax.devices(plat)
    devs = jax.devices()
    if len(devs) < min_count:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= len(devs):
                return cpu
        except RuntimeError:
            pass
    return devs


def device_platform(devices: list | None = None) -> str:
    """Platform of the analysis backend, WITHOUT importing jax in this
    process unless its backends are already initialized. Resolution
    order: explicit devices arg -> live in-process backends -> env pin
    string -> bounded subprocess probe (failed probe => "cpu"). This is
    the hang-safety boundary for `auto` resolution: a dead transport
    must yield a CPU verdict within the probe timeout, never an
    in-process jax.devices() call that can wedge forever."""
    if devices is not None:
        return devices[0].platform if devices else "none"
    if _backends_already_alive():
        import jax
        devs = jax.devices()
        return devs[0].platform if devs else "none"
    want = gates.get("JEPSEN_TPU_PLATFORM") \
        or os.environ.get("JAX_PLATFORMS")
    if want:
        plats = [p.strip() for p in want.split(",") if p.strip()]
        if plats and set(plats) <= {"cpu"}:
            return "cpu"
        # a pinned device transport (e.g. "axon,cpu") may be down:
        # fall through to the bounded probe rather than trusting it
    ok, err = probe_default_backend()
    if not ok:
        global backend_error
        backend_error = err
        return "cpu"
    return _probe_platform or "cpu"


def accelerator_available() -> bool:
    """True when a non-CPU backend is reachable — the `auto` checker
    backend resolves to the device kernels exactly when this holds.
    Bounded by the subprocess probe timeout; resolves jax-free, so a
    dead transport yields False instead of a wedged process."""
    try:
        return device_platform() not in ("cpu", "none")
    except Exception:
        return False


def resolve_backend(backend: str = "auto") -> str:
    """Resolve a checker backend choice to "tpu" (device kernels) or
    "cpu" (host oracles). "auto" — the default everywhere, mirroring the
    north star's `:backend :tpu` becoming the production analysis path —
    picks the device kernels when an accelerator is reachable, else the
    CPU oracle. JEPSEN_TPU_BACKEND overrides the auto resolution (the
    CLI's --backend flag sets it; tests force the device path on the
    virtual CPU mesh with it)."""
    if backend == "race":
        # the engine race is implemented by Linearizable.check_batch
        # (which intercepts "race" before resolving); every other
        # checker treats it as "auto" — device when reachable
        backend = "auto"
    if backend != "auto":
        return backend
    env = gates.get("JEPSEN_TPU_BACKEND")
    if env and env not in ("auto", "race"):
        return env
    return "tpu" if accelerator_available() else "cpu"
