"""Device discovery for the analysis data plane.

The default JAX backend wins (a real TPU slice when present), but:
  * JEPSEN_TPU_PLATFORM=cpu|tpu|... pins a platform explicitly (tests pin
    cpu so the 8-device virtual host mesh is used even on machines where
    a TPU plugin registers itself regardless of JAX_PLATFORMS), and
  * a minimum device count can be requested — if the preferred backend is
    smaller, we fall back to the host-platform devices, which honors
    --xla_force_host_platform_device_count virtual meshes.
"""

from __future__ import annotations

import os


def _pin_requested_platform() -> str | None:
    """Honor an explicit platform request even when a plugin (e.g. the
    axon TPU tunnel) has force-updated the jax_platforms config from
    sitecustomize, overriding the JAX_PLATFORMS env var. Without the
    re-pin, merely creating an array initializes every configured
    backend — and a dead tunnel hangs the process."""
    import jax

    plat = os.environ.get("JEPSEN_TPU_PLATFORM")
    want = plat or os.environ.get("JAX_PLATFORMS")
    if want and "axon" not in want and jax.config.jax_platforms != want:
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass
    return plat


def default_devices(min_count: int = 1) -> list:
    import jax

    plat = _pin_requested_platform()
    if plat:
        return jax.devices(plat)
    devs = jax.devices()
    if len(devs) < min_count:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= len(devs):
                return cpu
        except RuntimeError:
            pass
    return devs
