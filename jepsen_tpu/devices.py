"""Device discovery for the analysis data plane.

The default JAX backend wins (a real TPU slice when present), but:
  * JEPSEN_TPU_PLATFORM=cpu|tpu|... pins a platform explicitly (tests pin
    cpu so the 8-device virtual host mesh is used even on machines where
    a TPU plugin registers itself regardless of JAX_PLATFORMS),
  * a minimum device count can be requested — if the preferred backend is
    smaller, we fall back to the host-platform devices, which honors
    --xla_force_host_platform_device_count virtual meshes, and
  * initialization of an UNPINNED default backend is guarded by a
    bounded subprocess probe: a TPU plugin whose transport is down can
    hang `jax.devices()` indefinitely (it did, for 9+ minutes, in the
    round-2 bench), and a benchmark/checker must degrade to CPU with a
    structured error instead of hanging. The probe runs once per
    process and is memoized.
"""

from __future__ import annotations

import os
import subprocess
import sys

# Result of the one-shot default-backend probe: None = not yet run,
# (True, None) = healthy, (False, "err...") = dead/unreachable.
_probe_result: tuple[bool, str | None] | None = None

# Why the last default_devices() call fell back to CPU (None if it
# didn't). Benchmarks surface this in their structured output.
backend_error: str | None = None


def probe_timeout() -> float:
    return float(os.environ.get("JEPSEN_TPU_PROBE_TIMEOUT", "120"))


def _backends_already_alive() -> bool:
    """True when this process already initialized JAX backends — probing
    again would be pure waste (and the hang risk is already behind us)."""
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:
        return False


def probe_default_backend(timeout: float | None = None) -> tuple[bool, str | None]:
    """Initialize the default JAX backend in a THROWAWAY subprocess with a
    wall-clock bound. Returns (ok, error). Memoized per process.

    This is the only safe way to ask "is the TPU tunnel alive?": doing it
    in-process risks wedging the caller forever, because backend init
    holds the lock `jax.devices()` needs and a dead transport never
    returns."""
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    if _backends_already_alive():
        _probe_result = (True, None)
        return _probe_result
    timeout = probe_timeout() if timeout is None else timeout
    code = ("import jax; d = jax.devices(); "
            "print('JEPSEN_PROBE_OK', len(d), d[0].platform)")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
        if p.returncode == 0 and "JEPSEN_PROBE_OK" in p.stdout:
            _probe_result = (True, None)
        else:
            tail = (p.stderr or p.stdout).strip().splitlines()[-1:]
            _probe_result = (False, f"backend init failed (rc={p.returncode}): "
                                    f"{' '.join(tail)[:300]}")
    except subprocess.TimeoutExpired:
        _probe_result = (False, f"backend init hung > {timeout:.0f}s "
                                "(transport down?); falling back to cpu")
    except Exception as e:  # probe infrastructure itself failed
        _probe_result = (False, f"probe error: {e!r}"[:300])
    return _probe_result


def _pin_platform(want: str) -> None:
    """Pin jax_platforms even when a plugin (e.g. a TPU tunnel) has
    force-updated the config from sitecustomize, overriding the
    JAX_PLATFORMS env var. Without the re-pin, merely creating an array
    initializes every configured backend — and a dead tunnel hangs the
    process."""
    import jax
    if jax.config.jax_platforms != want:
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass


def _requested_platform() -> str | None:
    plat = os.environ.get("JEPSEN_TPU_PLATFORM")
    want = plat or os.environ.get("JAX_PLATFORMS")
    if want and "axon" not in want:
        _pin_platform(want)
    return plat


def ensure_platform_pin() -> None:
    """Re-assert the JEPSEN_TPU_PLATFORM/JAX_PLATFORMS env pin on the
    jax config. Kernel modules call this at import: plugins that
    force-update jax_platforms from sitecustomize otherwise win over
    the user's env var, and the first jit of ANY entry point would
    initialize the plugin backend (hanging the process when its
    transport is down). Cheap — a config write, no backend init."""
    _requested_platform()


def default_devices(min_count: int = 1, *, probe: bool = False) -> list:
    """The analysis devices. With probe=True (benchmarks, `auto` checker
    backends), an unpinned default backend is first health-checked in a
    bounded subprocess; on failure we pin cpu and record the reason in
    `devices.backend_error` instead of hanging."""
    global backend_error
    import jax

    plat = _requested_platform()
    if plat:
        return jax.devices(plat)
    if probe and not os.environ.get("JAX_PLATFORMS"):
        ok, err = probe_default_backend()
        if not ok:
            backend_error = err
            _pin_platform("cpu")
            return jax.devices("cpu")
    devs = jax.devices()
    if len(devs) < min_count:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= len(devs):
                return cpu
        except RuntimeError:
            pass
    return devs


def device_platform(devices: list | None = None) -> str:
    devs = devices if devices is not None else default_devices(probe=True)
    return devs[0].platform if devs else "none"


def accelerator_available() -> bool:
    """True when a non-CPU backend is reachable — the `auto` checker
    backend resolves to the device kernels exactly when this holds.
    Bounded: never hangs on a dead transport."""
    try:
        return device_platform() != "cpu"
    except Exception:
        return False


def resolve_backend(backend: str = "auto") -> str:
    """Resolve a checker backend choice to "tpu" (device kernels) or
    "cpu" (host oracles). "auto" — the default everywhere, mirroring the
    north star's `:backend :tpu` becoming the production analysis path —
    picks the device kernels when an accelerator is reachable, else the
    CPU oracle. JEPSEN_TPU_BACKEND overrides the auto resolution (the
    CLI's --backend flag sets it; tests force the device path on the
    virtual CPU mesh with it)."""
    if backend != "auto":
        return backend
    env = os.environ.get("JEPSEN_TPU_BACKEND")
    if env and env != "auto":
        return env
    return "tpu" if accelerator_available() else "cpu"
