"""Node scripting helpers: filesystem, downloads, users, daemons.

Counterpart of jepsen.control.util (jepsen/src/jepsen/control/util.clj):
everything here takes a Session and composes shell commands through it,
so all backends (ssh/local/dummy) work identically.
"""

from __future__ import annotations

import os.path
from typing import Iterable

from . import CommandError, Lit, Session, build_cmd


def exists(sess: Session, path: str) -> bool:
    """Does a file exist? (util.clj:19)"""
    return sess.exec_ok("test", "-e", path).ok


def tmp_dir(sess: Session, base: str = "/tmp/jepsen") -> str:
    """Create and return a fresh temp dir (util.clj:44)."""
    d = sess.exec("mktemp", "-d", f"{base}.XXXXXX")
    return d


def wget(sess: Session, url: str, dest: str | None = None,
         force: bool = False) -> str:
    """Download url on the node; returns the file path (util.clj:79)."""
    fname = dest or os.path.basename(url.split("?")[0])
    if force:
        sess.exec_ok("rm", "-f", fname)
    if not exists(sess, fname):
        sess.exec("wget", "--tries", "20", "--waitretry", "60",
                  "--retry-connrefused", "--no-check-certificate",
                  "-O", fname, url)
    return fname


CACHE_DIR = "/tmp/jepsen/wget-cache"


def cached_wget(sess: Session, url: str, force: bool = False) -> str:
    """Download url into a node-local cache; returns the cached path
    (util.clj:113)."""
    import hashlib
    name = hashlib.sha1(url.encode()).hexdigest()
    path = f"{CACHE_DIR}/{name}"
    if force:
        sess.exec_ok("rm", "-f", path)
    if not exists(sess, path):
        sess.exec("mkdir", "-p", CACHE_DIR)
        sess.exec("wget", "--tries", "20", "--waitretry", "60",
                  "--retry-connrefused", "--no-check-certificate",
                  "-O", path, url)
    return path


def install_archive(sess: Session, url: str, dest: str,
                    force: bool = False) -> str:
    """Download a tarball/zip and extract it to dest, stripping a single
    top-level directory if present (util.clj:145-220)."""
    sess.exec("mkdir", "-p", os.path.dirname(dest) or "/")
    if exists(sess, dest):
        if not force:
            return dest
        sess.exec("rm", "-rf", dest)
    archive = cached_wget(sess, url, force=force)
    tmp = tmp_dir(sess)
    try:
        if url.rstrip("/").endswith(".zip"):
            sess.exec("unzip", "-q", archive, "-d", tmp)
        else:
            sess.exec("tar", "-xf", archive, "-C", tmp)
        entries = sess.exec("ls", "-A", tmp).splitlines()
        if len(entries) == 1:
            sess.exec("mv", f"{tmp}/{entries[0]}", dest)
        else:
            sess.exec("mv", tmp, dest)
    finally:
        sess.exec_ok("rm", "-rf", tmp)
    return dest


def ensure_user(sess: Session, username: str) -> str:
    """Create a user if missing (util.clj:229)."""
    res = sess.exec_ok("id", "-u", username)
    if not res.ok:
        sess.exec("useradd", "--create-home", "--shell", "/bin/bash",
                  username)
    return username


def grepkill(sess: Session, pattern: str, signal: str = "KILL") -> None:
    """Kill processes matching a pattern (util.clj:238)."""
    sess.exec_ok(Lit(
        f"ps aux | grep {build_cmd(pattern)} | grep -v grep | "
        f"awk '{{print $2}}' | xargs -r kill -{signal}"))


def start_daemon(sess: Session, binary: str, *args,
                 pidfile: str, logfile: str, chdir: str | None = None,
                 env: dict | None = None, make_pidfile: bool = True) -> None:
    """Start a long-running process detached from the session, recording
    its pid and redirecting output (util.clj:262-291's
    start-stop-daemon, built on setsid+nohup so any backend works)."""
    envs = " ".join(f"{k}={build_cmd(v)}" for k, v in (env or {}).items())
    cd = f"cd {build_cmd(chdir)} && " if chdir else ""
    cmd = build_cmd(binary, *args)
    sess.exec(Lit(
        f"{cd}{envs}{' ' if envs else ''}"
        f"setsid nohup {cmd} >> {build_cmd(logfile)} 2>&1 < /dev/null & "
        + (f"echo $! > {build_cmd(pidfile)}" if make_pidfile else "true")))


def daemon_running(sess: Session, pidfile: str) -> bool:
    """Is the pidfile's process alive? (util.clj:307)"""
    res = sess.exec_ok(Lit(
        f"test -e {build_cmd(pidfile)} && "
        f"kill -0 $(cat {build_cmd(pidfile)})"))
    return res.ok


def stop_daemon(sess: Session, pidfile: str) -> None:
    """Kill the daemon's whole process group and remove the pidfile
    (util.clj:292-305)."""
    sess.exec_ok(Lit(
        f"test -e {build_cmd(pidfile)} && "
        f"kill -9 -- -$(ps -o pgid= -p $(cat {build_cmd(pidfile)}) "
        f"| tr -d ' ') ; rm -f {build_cmd(pidfile)}"))


def signal(sess: Session, process_name: str, sig: str) -> None:
    """Send a signal to processes by name (util.clj:320)."""
    sess.exec("pkill", f"-{sig}", "-f", process_name)
