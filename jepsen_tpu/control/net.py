"""Node network info: reachability and IP resolution.

Counterpart of jepsen.control.net (jepsen/src/jepsen/control/net.clj).
"""

from __future__ import annotations

import threading

from . import Session


def reachable(sess: Session, target: str) -> bool:
    """Can this node ping the target? (net.clj:8)"""
    return sess.exec_ok("ping", "-w", 1, "-c", 1, target).ok


def local_ip(sess: Session) -> str:
    """This node's primary IP (net.clj:14)."""
    out = sess.exec("hostname", "-I")
    return out.split()[0] if out else "127.0.0.1"


_ip_cache: dict[str, str] = {}
_ip_lock = threading.Lock()


def ip(sess: Session, hostname: str) -> str:
    """Resolve a hostname's IP from this node, memoized (net.clj:21-40)."""
    with _ip_lock:
        if hostname in _ip_cache:
            return _ip_cache[hostname]
    out = sess.exec("getent", "ahosts", hostname)
    addr = None
    for line in out.splitlines():
        parts = line.split()
        if parts and "STREAM" in line:
            addr = parts[0]
            break
    addr = addr or hostname
    with _ip_lock:
        _ip_cache[hostname] = addr
    return addr


def clear_ip_cache() -> None:
    with _ip_lock:
        _ip_cache.clear()
