"""L0: remote control — running commands on DB nodes.

Counterpart of the reference's jepsen.control
(jepsen/src/jepsen/control.clj): a `Remote` transport protocol
(connect/disconnect/execute/upload/download, control.clj:18-35) with three
backends:

  SSHRemote    shells out to the system ssh/scp binaries (OpenSSH), with
               connection multiplexing via ControlMaster for round-trip
               cost comparable to a persistent library connection
  LocalRemote  runs commands in a local subprocess (single-node dev)
  DummyRemote  records everything, does nothing (tests; the reference's
               --dummy mode, control.clj:38)

A `Session` wraps a Remote bound to one node and carries the sudo/cd
state (control.clj:122-137); `on_nodes` fans a function out over nodes in
parallel (control.clj:435-451). Failed executions raise CommandError
carrying the full command context, like the reference's slingshot maps.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from .. import gates as _gates
from ..util import real_pmap

DEFAULT_SSH_OPTS = (
    "-o", "StrictHostKeyChecking=no",
    "-o", "UserKnownHostsFile=/dev/null",
    "-o", "LogLevel=ERROR",
    "-o", "ConnectTimeout=10",
    "-o", "ServerAliveInterval=5",
)


class CommandError(Exception):
    """A remote command failed (nonzero exit, like control.clj throw+)."""

    def __init__(self, cmd: str, exit: int, out: str, err: str, node: str):
        super().__init__(
            f"command failed on {node} (exit {exit}): {cmd}\n"
            f"stdout: {out[:2000]}\nstderr: {err[:2000]}")
        self.cmd = cmd
        self.exit = exit
        self.out = out
        self.err = err
        self.node = node


class ConnectionError_(Exception):
    pass


@dataclass
class Result:
    out: str
    err: str
    exit: int

    @property
    def ok(self) -> bool:
        return self.exit == 0


class Remote:
    """Transport protocol. Implementations must be thread-safe per node."""

    def connect(self, conn_spec: dict) -> Any:
        """Open a connection handle for a node conn spec
        {node, user, port, password?, private_key_path?, dummy?}."""
        raise NotImplementedError

    def disconnect(self, handle: Any) -> None:
        pass

    def execute(self, handle: Any, cmd: str, stdin: str = "") -> Result:
        raise NotImplementedError

    def upload(self, handle: Any, local: str, remote: str) -> None:
        raise NotImplementedError

    def download(self, handle: Any, remote: str, local: str) -> None:
        raise NotImplementedError


class SSHRemote(Remote):
    """OpenSSH subprocess transport with ControlMaster multiplexing: the
    first command opens a persistent master connection; subsequent execs
    ride it (~ms instead of full handshakes)."""

    def __init__(self, control_dir: str | None = None):
        self.control_dir = control_dir or os.path.join(
            os.path.expanduser("~"), ".jepsen-tpu", "cm")
        os.makedirs(self.control_dir, mode=0o700, exist_ok=True)

    def _base_args(self, spec: dict) -> list[str]:
        args = list(DEFAULT_SSH_OPTS)
        sock = os.path.join(
            self.control_dir,
            f"{spec.get('user', 'root')}@{spec['node']}:{spec.get('port', 22)}")
        args += ["-o", "ControlMaster=auto", "-o", f"ControlPath={sock}",
                 "-o", "ControlPersist=60"]
        if spec.get("port"):
            args += ["-p", str(spec["port"])]
        if spec.get("private_key_path"):
            args += ["-i", spec["private_key_path"]]
        return args

    def _dest(self, spec: dict) -> str:
        return f"{spec.get('user', 'root')}@{spec['node']}"

    def connect(self, spec: dict) -> dict:
        return spec

    # ssh exits 255 for its OWN failures — but so may the remote
    # command. Disambiguate by echoing the command's exit status to
    # stderr from the remote shell: marker present = the command ran.
    # The marker string is a registered protocol constant
    # (gates.py: JEPSEN_TPU_EC) so the namespace scanner accounts
    # for it.
    _EC_MARK = _gates.get("JEPSEN_TPU_EC")

    def execute(self, spec: dict, cmd: str, stdin: str = "") -> Result:
        wrapped = (f"( {cmd}\n); __jec=$?; "
                   f"echo '{self._EC_MARK}'$__jec >&2; exit $__jec")
        argv = ["ssh", *self._base_args(spec), self._dest(spec), wrapped]
        p = subprocess.run(argv, input=stdin, capture_output=True,
                           text=True, timeout=spec.get("timeout", 300))
        remote_ec = None
        err_lines = []
        for ln in p.stderr.splitlines():
            if ln.startswith(self._EC_MARK):
                try:
                    remote_ec = int(ln[len(self._EC_MARK):])
                except ValueError:
                    pass
            else:
                err_lines.append(ln)
        err = "\n".join(err_lines)
        if p.returncode == 255 and remote_ec != 255:
            raise ConnectionError_(err.strip())
        return Result(p.stdout, err, p.returncode)

    def _scp_args(self, spec: dict) -> list[str]:
        args = [a if a != "-p" else "-P" for a in self._base_args(spec)]
        return args

    def upload(self, spec: dict, local: str, remote: str) -> None:
        argv = ["scp", *self._scp_args(spec), local,
                f"{self._dest(spec)}:{remote}"]
        p = subprocess.run(argv, capture_output=True, text=True)
        if p.returncode != 0:
            raise ConnectionError_(f"upload failed: {p.stderr.strip()}")

    def download(self, spec: dict, remote: str, local: str) -> None:
        argv = ["scp", *self._scp_args(spec),
                f"{self._dest(spec)}:{remote}", local]
        p = subprocess.run(argv, capture_output=True, text=True)
        if p.returncode != 0:
            raise ConnectionError_(f"download failed: {p.stderr.strip()}")


class LocalRemote(Remote):
    """Runs commands locally — the single-node / development backend."""

    def connect(self, spec: dict) -> dict:
        return spec

    def execute(self, spec: dict, cmd: str, stdin: str = "") -> Result:
        p = subprocess.run(["bash", "-c", cmd], input=stdin,
                           capture_output=True, text=True,
                           timeout=spec.get("timeout", 300))
        return Result(p.stdout, p.stderr, p.returncode)

    def upload(self, spec: dict, local: str, remote: str) -> None:
        subprocess.run(["cp", "-r", local, remote], check=True)

    def download(self, spec: dict, remote: str, local: str) -> None:
        subprocess.run(["cp", "-r", remote, local], check=True)


class DummyRemote(Remote):
    """Records every action; all commands succeed with empty output
    (control.clj:38 --dummy mode). `actions` is a list of
    (node, kind, payload) tuples shared across sessions."""

    def __init__(self):
        self.actions: list[tuple] = []
        self.lock = threading.Lock()
        self.responses: dict[str, str] = {}

    def _record(self, node, kind, payload):
        with self.lock:
            self.actions.append((node, kind, payload))

    def connect(self, spec: dict) -> dict:
        self._record(spec["node"], "connect", None)
        return spec

    def disconnect(self, spec: dict) -> None:
        self._record(spec["node"], "disconnect", None)

    def execute(self, spec: dict, cmd: str, stdin: str = "") -> Result:
        self._record(spec["node"], "execute", cmd)
        for pattern, out in self.responses.items():
            if pattern in cmd:
                return Result(out, "", 0)
        return Result("", "", 0)

    def upload(self, spec: dict, local: str, remote: str) -> None:
        self._record(spec["node"], "upload", (local, remote))

    def download(self, spec: dict, remote: str, local: str) -> None:
        self._record(spec["node"], "download", (remote, local))


def escape(arg: Any) -> str:
    """Shell-escape one argument (control.clj:77-120)."""
    return shlex.quote(str(arg))


def build_cmd(*args: Any) -> str:
    """Join arguments into an escaped command string. Strings containing
    no specials pass through bare; everything else is quoted. Lists are
    flattened."""
    parts: list[str] = []
    for a in args:
        if isinstance(a, (list, tuple)):
            parts.append(build_cmd(*a))
        elif isinstance(a, Lit):
            parts.append(a.s)
        else:
            parts.append(escape(a))
    return " ".join(parts)


@dataclass
class Lit:
    """A literal, unescaped command fragment (control.clj `lit`)."""

    s: str


@dataclass
class Session:
    """A control session: a Remote handle plus sudo/cd/env state."""

    remote: Remote
    spec: dict
    handle: Any = None
    sudo_user: str | None = None
    sudo_password: str | None = None
    dir: str | None = None
    retries: int = 3
    retry_backoff: float = 0.1
    _lock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def node(self) -> str:
        return self.spec["node"]

    def connect(self) -> "Session":
        self.handle = self.remote.connect(self.spec)
        return self

    def disconnect(self) -> None:
        if self.handle is not None:
            self.remote.disconnect(self.handle)
            self.handle = None

    # -- command wrapping (control.clj:122-137) ---------------------------

    def _wrap(self, cmd: str) -> tuple[str, str]:
        stdin = ""
        if self.dir:
            cmd = f"cd {escape(self.dir)} && {cmd}"
        if self.sudo_user:
            stdin = (self.sudo_password + "\n") if self.sudo_password else ""
            cmd = f"sudo -S -u {escape(self.sudo_user)} bash -c {escape(cmd)}"
        return cmd, stdin

    def _with_reconnect(self, f: Callable[[], Any]) -> Any:
        """Retry transport failures with reconnects (reconnect.clj:92-129,
        control.clj:168-189). Command *timeouts* are NOT retried — the
        remote side effects may have happened, and re-executing a
        non-idempotent command (a clock bump, a daemon start) would
        silently corrupt the test; TimeoutExpired propagates."""
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            try:
                return f()
            except ConnectionError_ as e:
                last = e
                time.sleep(self.retry_backoff * (attempt + 1))
                try:
                    self.connect()
                except Exception:
                    pass
        raise ConnectionError_(
            f"giving up on {self.node} after {self.retries + 1} attempts: "
            f"{last}")

    # -- public API -------------------------------------------------------

    def exec_raw(self, cmd: str) -> Result:
        if self.handle is None:
            self.connect()
        wrapped, stdin = self._wrap(cmd)
        return self._with_reconnect(
            lambda: self.remote.execute(self.handle, wrapped, stdin))

    def exec(self, *args: Any) -> str:
        """Run a command; return trimmed stdout; raise CommandError on
        nonzero exit (control.clj exec, :204)."""
        cmd = build_cmd(*args)
        res = self.exec_raw(cmd)
        if res.exit != 0:
            raise CommandError(cmd, res.exit, res.out, res.err, self.node)
        return res.out.strip()

    def exec_ok(self, *args: Any) -> Result:
        """Run a command, returning the Result without raising."""
        return self.exec_raw(build_cmd(*args))

    def su(self, user: str = "root", password: str | None = None) -> "Session":
        """A session running commands as `user` (control.clj su, :294)."""
        return Session(self.remote, self.spec, self.handle, user,
                       password or self.sudo_password, self.dir,
                       self.retries, self.retry_backoff)

    def cd(self, dir: str) -> "Session":
        return Session(self.remote, self.spec, self.handle, self.sudo_user,
                       self.sudo_password, dir, self.retries,
                       self.retry_backoff)

    def upload(self, local: str, remote_path: str) -> None:
        if self.handle is None:
            self.connect()
        self._with_reconnect(
            lambda: self.remote.upload(self.handle, local, remote_path))

    def download(self, remote_path: str, local: str) -> None:
        if self.handle is None:
            self.connect()
        self._with_reconnect(
            lambda: self.remote.download(self.handle, remote_path, local))


def conn_spec(test: dict, node: str) -> dict:
    ssh = test.get("ssh", {})
    return {"node": node,
            "user": ssh.get("username", "root"),
            "port": ssh.get("port", 22),
            "password": ssh.get("password"),
            "private_key_path": ssh.get("private_key_path"),
            "strict_host_key_checking": ssh.get("strict_host_key_checking",
                                                False)}


def remote_for(test: dict) -> Remote:
    """Pick a Remote backend from the test map: an explicit :remote wins;
    dummy mode uses DummyRemote (recorded on the test for inspection)."""
    r = test.get("remote")
    if r is not None:
        return r
    if test.get("ssh", {}).get("dummy"):
        r = DummyRemote()
        test["remote"] = r
        return r
    r = SSHRemote()
    test["remote"] = r
    return r


def session(test: dict, node: str) -> Session:
    return Session(remote_for(test), conn_spec(test, node))


def on_nodes(test: dict, f: Callable[[dict, str], Any],
             nodes: list[str] | None = None) -> dict:
    """Evaluate f(test, node) in parallel on each node, with a control
    session bound; returns {node: result} (control.clj:435-451)."""
    nodes = list(nodes if nodes is not None else test.get("nodes", []))

    def run1(node: str):
        sess = session(test, node)
        try:
            token = _current.set(sess)
            try:
                return f(test, node)
            finally:
                _current.reset(token)
        finally:
            sess.disconnect()

    return dict(zip(nodes, real_pmap(run1, nodes)))


# -- implicit current session (the reference's dynamic *session* var) -----

import contextvars

_current: contextvars.ContextVar[Session | None] = \
    contextvars.ContextVar("jepsen_control_session", default=None)


def current_session() -> Session:
    s = _current.get()
    if s is None:
        raise RuntimeError("no control session bound; use on_nodes or "
                           "bind_session")
    return s


class bind_session:
    """Context manager binding the implicit session:
    with control.bind_session(sess): control.exec("ls")."""

    def __init__(self, sess: Session):
        self.sess = sess
        self.token = None

    def __enter__(self):
        self.token = _current.set(self.sess)
        return self.sess

    def __exit__(self, *exc):
        _current.reset(self.token)
        return False


def exec(*args: Any) -> str:  # noqa: A001 - mirrors the reference's name
    return current_session().exec(*args)


def sudo_exec(*args: Any) -> str:
    return current_session().su().exec(*args)


def upload(local: str, remote_path: str) -> None:
    current_session().upload(local, remote_path)


def download(remote_path: str, local: str) -> None:
    current_session().download(remote_path, local)