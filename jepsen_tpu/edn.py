"""EDN reader/writer.

The reference framework persists histories and results as EDN
(`history.edn`, `results.edn`; reference: jepsen/src/jepsen/store.clj:195-239)
so this codec exists for store compatibility: our framework can re-analyze
histories recorded by the reference and emit artifacts the reference's
tooling can read.

Design choices:
  * Keywords and symbols are str subclasses (`Keyword`, `Symbol`), so
    ``Keyword("ok") == "ok"`` — internal code works with plain strings while
    the printer still round-trips ``:ok``.
  * Tagged literals (``#foo/Bar {...}``) parse to `Tagged(tag, value)` unless
    a reader is registered; record tags like ``#knossos.model.CASRegister{}``
    are revived to plain dicts with the tag attached (mirroring the
    defrecord-reviving reader in the reference store, store.clj:195-239).
"""

from __future__ import annotations

import datetime
import io
import math
from typing import Any, Callable


class Keyword(str):
    """An EDN keyword. Compares equal to its name string."""

    __slots__ = ()
    _interned: dict[str, "Keyword"] = {}

    def __new__(cls, name: str) -> "Keyword":
        k = cls._interned.get(name)
        if k is None:
            k = super().__new__(cls, name)
            cls._interned[name] = k
        return k

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f":{str.__str__(self)}"


class Symbol(str):
    """An EDN symbol. Compares equal to its name string."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return str.__str__(self)


class Tagged:
    """A tagged literal the reader had no handler for."""

    __slots__ = ("tag", "value")

    def __init__(self, tag: str, value: Any):
        self.tag = tag
        self.value = value

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, Tagged)
            and self.tag == other.tag
            and self.value == other.value
        )

    def __hash__(self) -> int:
        return hash((self.tag, _hashable(self.value)))

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"#{self.tag} {self.value!r}"


def _hashable(v: Any) -> Any:
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return frozenset(_hashable(x) for x in v)
    return v


_WS = " \t\r\n,"
_CHAR_NAMES = {
    "newline": "\n",
    "return": "\r",
    "space": " ",
    "tab": "\t",
    "backspace": "\b",
    "formfeed": "\f",
}


def _default_inst(s: str) -> datetime.datetime:
    # EDN instants are RFC-3339; datetime.fromisoformat handles the common
    # forms once a trailing Z is normalized.
    return datetime.datetime.fromisoformat(s.replace("Z", "+00:00"))


DEFAULT_READERS: dict[str, Callable[[Any], Any]] = {
    "inst": _default_inst,
    "uuid": lambda s: s,
}


class _Reader:
    def __init__(self, text: str, readers: dict[str, Callable[[Any], Any]]):
        self.text = text
        self.pos = 0
        self.n = len(text)
        self.readers = readers

    def error(self, msg: str) -> Exception:
        line = self.text.count("\n", 0, self.pos) + 1
        return ValueError(f"EDN parse error at line {line} (pos {self.pos}): {msg}")

    def peek(self) -> str:
        return self.text[self.pos] if self.pos < self.n else ""

    def next_ch(self) -> str:
        ch = self.text[self.pos]
        self.pos += 1
        return ch

    def skip_ws(self) -> None:
        while self.pos < self.n:
            ch = self.text[self.pos]
            if ch in _WS:
                self.pos += 1
            elif ch == ";":
                nl = self.text.find("\n", self.pos)
                self.pos = self.n if nl < 0 else nl + 1
            else:
                return

    def read(self) -> Any:
        self.skip_ws()
        if self.pos >= self.n:
            raise self.error("unexpected end of input")
        ch = self.peek()
        if ch == "(":
            self.pos += 1
            return tuple(self.read_seq(")"))
        if ch == "[":
            self.pos += 1
            return self.read_seq("]")
        if ch == "{":
            self.pos += 1
            return self.read_map()
        if ch == '"':
            return self.read_string()
        if ch == ":":
            self.pos += 1
            return Keyword(self.read_token())
        if ch == "\\":
            return self.read_char()
        if ch == "#":
            return self.read_dispatch()
        if ch in ")]}":
            raise self.error(f"unmatched delimiter {ch!r}")
        return self.read_atom()

    def read_seq(self, closer: str) -> list:
        out = []
        while True:
            self.skip_ws()
            if self.pos >= self.n:
                raise self.error(f"expected {closer!r}")
            if self.peek() == closer:
                self.pos += 1
                return out
            v = self.read()
            if v is not _DISCARDED:
                out.append(v)

    def read_map(self) -> dict:
        items = self.read_seq("}")
        if len(items) % 2:
            raise self.error("map literal with odd number of forms")
        out = {}
        for i in range(0, len(items), 2):
            out[_as_key(items[i])] = items[i + 1]
        return out

    def read_string(self) -> str:
        self.pos += 1  # opening quote
        buf = io.StringIO()
        while True:
            if self.pos >= self.n:
                raise self.error("unterminated string")
            ch = self.next_ch()
            if ch == '"':
                return buf.getvalue()
            if ch == "\\":
                esc = self.next_ch()
                if esc == "n":
                    buf.write("\n")
                elif esc == "t":
                    buf.write("\t")
                elif esc == "r":
                    buf.write("\r")
                elif esc == "b":
                    buf.write("\b")
                elif esc == "f":
                    buf.write("\f")
                elif esc == "u":
                    code = self.text[self.pos : self.pos + 4]
                    if len(code) < 4 or not all(c in "0123456789abcdefABCDEF"
                                                for c in code):
                        raise self.error(f"bad unicode escape \\u{code!r}")
                    self.pos += 4
                    buf.write(chr(int(code, 16)))
                else:
                    buf.write(esc)
            else:
                buf.write(ch)

    def read_char(self) -> str:
        self.pos += 1  # backslash
        start = self.pos
        # A char is either a named char or a single character.
        while self.pos < self.n and self.text[self.pos] not in _WS + '()[]{}";':
            self.pos += 1
        tok = self.text[start : self.pos]
        if len(tok) <= 1:
            if not tok:
                raise self.error("bad character literal")
            return tok
        if tok in _CHAR_NAMES:
            return _CHAR_NAMES[tok]
        if tok.startswith("u") and len(tok) == 5:
            return chr(int(tok[1:], 16))
        # Multi-char but unknown: take first char, rewind rest.
        self.pos = start + 1
        return tok[0]

    def read_dispatch(self) -> Any:
        self.pos += 1  # '#'
        ch = self.peek()
        if ch == "#":  # symbolic values: ##NaN ##Inf ##-Inf
            self.pos += 1
            tok = self.read_token()
            if tok == "NaN":
                return math.nan
            if tok == "Inf":
                return math.inf
            if tok == "-Inf":
                return -math.inf
            raise self.error(f"unknown symbolic value ##{tok}")
        if ch == "{":
            self.pos += 1
            return frozenset(_as_key(v) for v in self.read_seq("}"))
        if ch == "_":
            self.pos += 1
            self.read()  # discard next form
            return _DISCARDED
        # Tagged literal: #tag value, including record syntax #ns.Rec{...}.
        tag = self.read_token()
        value = self.read()
        reader = self.readers.get(tag)
        if reader is not None:
            return reader(value)
        if isinstance(value, dict):
            # Record-style: revive as a dict, remembering its type.
            out = dict(value)
            out[Keyword("edn/tag")] = tag
            return out
        return Tagged(tag, value)

    def read_token(self) -> str:
        start = self.pos
        while self.pos < self.n:
            ch = self.text[self.pos]
            if ch in _WS or ch in '()[]";' or ch in "}]":
                break
            if ch == "{":  # record literal opens right after the tag
                break
            self.pos += 1
        if self.pos == start:
            raise self.error("empty token")
        return self.text[start : self.pos]

    def read_atom(self) -> Any:
        tok = self.read_token()
        if tok == "nil":
            return None
        if tok == "true":
            return True
        if tok == "false":
            return False
        first = tok[0]
        if first.isdigit() or (first in "+-" and len(tok) > 1 and tok[1].isdigit()):
            return _parse_number(tok, self)
        return Symbol(tok)


def _parse_number(tok: str, rdr: _Reader) -> Any:
    if tok.endswith("N"):
        return int(tok[:-1])
    if tok.endswith("M"):
        return float(tok[:-1])
    if "/" in tok:  # ratio
        num, den = tok.split("/")
        return int(num) / int(den)
    try:
        if any(c in tok for c in ".eE") and not tok.startswith("0x"):
            return float(tok)
        return int(tok, 0) if tok.startswith(("0x", "-0x", "+0x")) else int(tok)
    except ValueError as e:
        raise rdr.error(f"bad number {tok!r}") from e


def _as_key(v: Any) -> Any:
    """Make a parsed value usable as a dict key / set member."""
    if isinstance(v, list):
        return tuple(_as_key(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _as_key(x)) for k, x in v.items()))
    return v


class _Discarded:
    __slots__ = ()


_DISCARDED = _Discarded()


def loads(text: str, readers: dict[str, Callable[[Any], Any]] | None = None) -> Any:
    """Parse a single EDN form from `text`."""
    r = _Reader(text, {**DEFAULT_READERS, **(readers or {})})
    v = r.read()
    while v is _DISCARDED:
        v = r.read()
    return v


def loads_all(text: str, readers=None) -> list:
    """Parse every top-level EDN form in `text` (e.g. a history.edn file)."""
    r = _Reader(text, {**DEFAULT_READERS, **(readers or {})})
    out = []
    while True:
        r.skip_ws()
        if r.pos >= r.n:
            return out
        v = r.read()
        if v is not _DISCARDED:
            out.append(v)


_STR_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n", "\t": "\\t", "\r": "\\r"}


def _dump(v: Any, out: io.StringIO, keywordize: bool) -> None:
    if v is None:
        out.write("nil")
    elif v is True:
        out.write("true")
    elif v is False:
        out.write("false")
    elif isinstance(v, Keyword):
        out.write(":" + str.__str__(v))
    elif isinstance(v, Symbol):
        out.write(str.__str__(v))
    elif isinstance(v, str):
        if keywordize and _keyword_safe(v):
            out.write(":" + v)
        else:
            out.write('"' + "".join(_STR_ESC.get(c, c) for c in v) + '"')
    elif isinstance(v, bool):  # pragma: no cover - caught above
        out.write("true" if v else "false")
    elif isinstance(v, int):
        out.write(str(v))
    elif isinstance(v, float):
        if math.isnan(v):
            out.write("##NaN")
        elif math.isinf(v):
            out.write("##Inf" if v > 0 else "##-Inf")
        else:
            out.write(repr(v))
    elif isinstance(v, dict):
        out.write("{")
        for i, (k, x) in enumerate(v.items()):
            if i:
                out.write(", ")
            _dump(k, out, keywordize)
            out.write(" ")
            _dump(x, out, keywordize)
        out.write("}")
    elif isinstance(v, (list, tuple)):
        out.write("[")
        for i, x in enumerate(v):
            if i:
                out.write(" ")
            _dump(x, out, keywordize)
        out.write("]")
    elif isinstance(v, (set, frozenset)):
        out.write("#{")
        for i, x in enumerate(sorted(v, key=repr)):
            if i:
                out.write(" ")
            _dump(x, out, keywordize)
        out.write("}")
    elif isinstance(v, Tagged):
        out.write(f"#{v.tag} ")
        _dump(v.value, out, keywordize)
    elif isinstance(v, datetime.datetime):
        out.write(f'#inst "{v.isoformat()}"')
    else:
        # Fall back to the repr as a string — never crash a store write.
        _dump(repr(v), out, False)


def _keyword_safe(s: str) -> bool:
    if not s:
        return False
    if s[0].isdigit() or s[0] == ":":
        return False
    return all(c.isalnum() or c in "-_.*+!?<>=/$&" for c in s)


def dumps(v: Any, keywordize: bool = False) -> str:
    """Serialize `v` to EDN.

    With `keywordize=True`, bare strings that look like keywords are emitted
    as keywords — this makes dict-based op maps round-trip to idiomatic
    history.edn (:type :invoke, ...) without an explicit Keyword wrapper at
    every call site.
    """
    out = io.StringIO()
    _dump(v, out, keywordize)
    return out.getvalue()
