"""Zero-copy shared-memory transport for pool-encoded histories.

The pipelined store sweep (ingest.iter_encode_chunks) used to move
every EncodedHistory through `multiprocessing.Pool`'s result pipe:
each worker pickled its arrays, the parent unpickled them SERIALLY on
the thread that also packs and dispatches to the device — for a
256x5000-txn sweep that serial unpickle alone is tens of seconds of
pure copy (the 40 s host gap of BENCH_r05_hw.json). Here workers
instead write the encoded arrays once into a POSIX shared-memory
segment and send only a tiny descriptor — (segment name, per-field
offset/shape/dtype) — over the pipe; the parent maps the segment and
wraps numpy views around the SAME pages, so the bytes cross the
process boundary zero-copy and the parent's per-history cost is a few
dict lookups.

Leak discipline (the part shared memory is notorious for): the PARENT
pre-generates every segment name and hands it to the worker with the
task, so the parent can always enumerate — and unlink — segments that
were created but never consumed (worker crash, mid-stream pool
failure, caller abandoning the iterator). On the happy path the parent
unlinks each segment the moment it maps it: POSIX keeps the pages
alive until the last mapping dies, so the name never outlives one
round-trip and nothing is left in /dev/shm even on SIGKILL of a
worker. Workers unregister their create from multiprocessing's
resource_tracker (the tracker would otherwise unlink parent-held
segments when a pool worker exits).

`JEPSEN_TPU_SHM_INGEST=0` (or an unusable /dev/shm — probed once per
process) falls back to the classic pickle transport; the pipeline is
identical either way, only the byte path differs.
"""

from __future__ import annotations

import logging
import os
import uuid
from typing import Any

import numpy as np

log = logging.getLogger(__name__)

#: Every segment this module creates carries this prefix, so leak
#: checks (tests, ops) can scan /dev/shm for strays attributably.
NAME_PREFIX = "jtshm"

# The array fields moved through the segment come from the ONE
# canonical layout (store.ENCODED_FIELDS — shared with the encoded.v1
# sidecar cache). Everything else (key_names, anomalies, scalars)
# rides the descriptor: those are tiny, and only the arrays are worth
# zero-copying.


def enabled() -> bool:
    """One home for the JEPSEN_TPU_SHM_INGEST gate (default on)."""
    from . import gates
    return gates.get("JEPSEN_TPU_SHM_INGEST")


_probe: bool | None = None


def available() -> bool:
    """Can this host actually create shared memory? Probed once per
    process (containers sometimes mount /dev/shm noexec/ro or size 0);
    a False here routes ingest to the pickle transport instead of
    letting every worker die on ENOSPC."""
    global _probe
    if _probe is None:
        try:
            from multiprocessing import shared_memory as _sm
            seg = _sm.SharedMemory(create=True, size=16)
            seg.close()
            seg.unlink()
            _probe = True
        except Exception as e:
            log.info("shared memory unavailable (%r); ingest falls "
                     "back to pickle transport", e)
            _probe = False
    return _probe


def gen_name() -> str:
    """A parent-chosen segment name: unique, attributable, and known
    to the parent BEFORE the worker creates it (the leak-sweep
    contract in the module docstring)."""
    return f"{NAME_PREFIX}_{os.getpid()}_{uuid.uuid4().hex[:12]}"


def _untrack(seg) -> None:
    """Detach a segment from multiprocessing's resource_tracker: the
    creating WORKER must not let the (process-shared) tracker unlink a
    segment the parent still needs when the worker exits. Best-effort:
    the tracker API is semi-private, and on failure the cost is a
    spurious cleanup warning, not a leak."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass


def export(enc, name: str, checker: str):
    """Worker side: copy `enc`'s arrays into a fresh segment `name`
    and return the descriptor dict. Any failure (shm mount full,
    unexpected field) degrades to returning `enc` itself — the item
    then rides the pickle pipe like before, per-item."""
    from . import store as _store
    if checker not in _store.ENCODED_FIELDS:
        return enc
    try:
        arrays = _store.encoded_arrays(enc, checker)
        off = 0
        layout = []
        for f, a in arrays:
            off = (off + 7) & ~7           # 8-byte align every field
            layout.append((f, off, a.shape, a.dtype.str))
            off += a.nbytes
        from multiprocessing import shared_memory as _sm
        seg = _sm.SharedMemory(name=name, create=True, size=max(1, off))
        _untrack(seg)
        try:
            for (f, a), (_f, o, _s, _d) in zip(arrays, layout):
                if a.nbytes:
                    # single memcpy into the segment (a is contiguous;
                    # tobytes() here would materialize a second copy)
                    seg.buf[o:o + a.nbytes] = memoryview(a).cast("B")
        finally:
            seg.close()
        if checker == "wr":
            meta = {"n": enc.n, "key_count": enc.key_count,
                    "anomalies": enc.anomalies}
        else:
            meta = {"n": enc.n, "n_keys": enc.n_keys,
                    "max_pos": enc.max_pos, "key_names": enc.key_names,
                    "anomalies": enc.anomalies}
        return {"__jt_shm__": True, "name": name, "checker": checker,
                "fields": layout, "nbytes": off, "meta": meta,
                # cache-hit provenance survives the transport: the
                # parent's warm_copy_bytes attribution needs to know
                # this encoding came from a sidecar even though the
                # rebuild makes fresh view objects
                "warm": bool(getattr(enc, "warm", False))}
    except Exception as e:
        log.debug("shm export failed (%r); item falls back to pickle",
                  e)
        try:
            unlink_stale(name)
        except Exception:
            pass
        return enc


def is_descriptor(payload) -> bool:
    return isinstance(payload, dict) and payload.get("__jt_shm__")


# -- sidecar references ----------------------------------------------------
#
# A warm v2 cache hit must NOT ride shared memory: the worker's mmap
# views would be memcpy'd into a segment and the parent's "zero-copy"
# views would alias that copy — the exact host copy the dispatch-shaped
# sidecar exists to remove, plus the parent-side encoding would lose
# its `.dispatch` views entirely. Instead the worker sends a tiny
# REFERENCE (run dir + checker) and the parent mmaps the sidecar
# itself, so the pages the pack stage hands to device_put are the
# parent's own mapping of the on-disk cache. The parent re-validates
# the cache key on materialize (bounded hash — microseconds), so a
# history rewritten between the worker's check and the parent's map
# degrades to a re-encode, never to stale tensors.

def sidecar_ref(run_dir, checker: str) -> dict:
    """Worker side: the descriptor for a dispatch-shaped cache hit."""
    return {"__jt_sidecar__": True, "dir": str(run_dir),
            "checker": checker}


def is_sidecar_ref(payload) -> bool:
    return isinstance(payload, dict) and payload.get("__jt_sidecar__")


def materialize_sidecar(ref: dict):
    """Parent side: mmap the referenced sidecar. Falls back to a full
    in-parent encode when the sidecar vanished or re-keyed between the
    worker's hit and now (rare; correctness over speed)."""
    from . import store as _store
    enc = _store.load_encoded(ref["dir"], ref["checker"])
    if enc is not None:
        return enc
    from .ingest import encode_run_dir
    try:
        return encode_run_dir(ref["dir"], ref["checker"])
    except Exception as e:
        return e


def _orphan(seg) -> None:
    """Hand the segment's mapping over to the numpy views built on it:
    neuter the SharedMemory object so neither GC nor close() can
    unmap pages the views still reference (mmap teardown then happens
    naturally when the last array dies). The fd is closed now — a
    sweep over thousands of runs must not hold thousands of fds."""
    try:
        if seg._fd >= 0:
            os.close(seg._fd)
            seg._fd = -1
    except OSError:
        pass
    seg._buf = None
    seg._mmap = None


def materialize(desc: dict):
    """Parent side: map the descriptor's segment, UNLINK it
    immediately (pages survive until the views die; the name must
    never outlive this call), and rebuild the encoding with zero-copy
    numpy views over the shared pages. The attach rides a short
    jittered-exponential retry: a transiently starved host (EMFILE,
    ENOMEM under pressure) recovers, while a genuinely missing
    segment (FileNotFoundError) fails straight through — it can only
    mean the descriptor outlived its pages, and waiting won't bring
    them back."""
    from multiprocessing import shared_memory as _sm

    from .util import with_retry
    seg = with_retry(lambda: _sm.SharedMemory(name=desc["name"]),
                     retries=3, backoff=0.005, exceptions=(OSError,),
                     exponential=True, fatal=(FileNotFoundError,))
    try:
        seg.unlink()
    except FileNotFoundError:
        pass
    buf = seg.buf
    arrays: dict[str, Any] = {}
    for f, off, shape, dt in desc["fields"]:
        n = int(np.prod(shape)) if shape else 1
        arrays[f] = np.frombuffer(buf, dtype=np.dtype(dt), count=n,
                                  offset=off).reshape(shape)
    _orphan(seg)
    from . import store as _store
    enc = _store.rebuild_encoded(desc["checker"], arrays,
                                 desc["meta"])
    if desc.get("warm"):
        enc.warm = True
    return enc


def _pid_alive(pid: int) -> bool:
    """Is `pid` a live process? Permission errors mean alive (someone
    else's process); any other failure errs on the safe side."""
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True


def reclaim_stale(shm_dir: str = "/dev/shm") -> int:
    """Sweep-start reclamation: unlink every `jtshm_<pid>_*` segment
    whose creating pid is DEAD — the parent-pregenerated names a
    previous run left behind when it crashed between a worker's create
    and the parent's materialize (SIGKILL of the whole sweep, OOM
    kill). Segments of live pids (a concurrent sweep on the same
    host) and foreign names are untouched, so /dev/shm can't leak
    across runs yet two sweeps can share a box. Returns the count
    reclaimed (callers attribute it as the `shm_stale_reclaimed`
    counter)."""
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return 0
    n = 0
    for name in names:
        if not name.startswith(NAME_PREFIX + "_"):
            continue
        parts = name.split("_")
        try:
            pid = int(parts[1])
        except (IndexError, ValueError):
            continue
        if pid == os.getpid() or _pid_alive(pid):
            continue
        if unlink_stale(name):
            log.info("reclaimed stale shm segment %s (pid %d dead)",
                     name, pid)
            n += 1
    return n


def unlink_stale(name: str) -> bool:
    """Best-effort unlink of a segment the parent never consumed (the
    exception-path sweep). True if a segment was actually removed."""
    from multiprocessing import shared_memory as _sm
    try:
        seg = _sm.SharedMemory(name=name)
    except FileNotFoundError:
        return False
    except Exception:
        return False
    try:
        seg.close()
    except Exception:
        pass
    try:
        seg.unlink()
        return True
    except Exception:
        return False
