"""RabbitMQ suite.

Counterpart of rabbitmq/src/jepsen/rabbitmq.clj: apt-installed broker
cluster, a durable queue driven by publish/get/ack (dequeue!,
rabbitmq.clj:104-133), total-queue checking. The client speaks AMQP
0-9-1 directly (drivers.amqp) instead of langohr.
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis, os_setup
from ..drivers import DBError, DriverError
from ..workloads import queue as queue_wl
from . import base_opts, nemesis_cycle
from .sql import resolve

QUEUE = "jepsen.queue"
LOGFILE = "/var/log/rabbitmq/rabbit.log"


class RabbitDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """apt install + erlang cookie + join_cluster fan-in
    (db, rabbitmq.clj:30-100); kill/pause fault protocols via
    SignalProcess (the beam VM hosts the broker, so signals target
    the rabbitmq process tree)."""

    process_pattern = "rabbitmq"

    def _start(self, sess, test, node):
        sess.exec("service", "rabbitmq-server", "start")

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "rabbitmq-server")
        # one shared erlang cookie, then every non-primary joins node 0
        sess.exec("service", "rabbitmq-server", "stop")
        sess.exec("sh", "-c",
                  "echo jepsenrabbitcookie > /var/lib/rabbitmq/.erlang.cookie")
        sess.exec("chmod", "400", "/var/lib/rabbitmq/.erlang.cookie")
        sess.exec("chown", "rabbitmq:rabbitmq",
                  "/var/lib/rabbitmq/.erlang.cookie")
        sess.exec("service", "rabbitmq-server", "start")
        nodes = test.get("nodes", [node])
        if node != nodes[0]:
            sess.exec("rabbitmqctl", "stop_app")
            sess.exec("rabbitmqctl", "join_cluster",
                      f"rabbit@{nodes[0]}")
            sess.exec("rabbitmqctl", "start_app")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("rabbitmqctl", "stop_app")
        sess.exec_ok("rabbitmqctl", "reset")
        sess.exec_ok("service", "rabbitmq-server", "stop")

    def log_files(self, test, node):
        return [LOGFILE]


class AckIndeterminate(Exception):
    """basic.get delivered a message but the ack outcome is unknown."""

    def __init__(self, value):
        super().__init__("ack outcome unknown")
        self.value = value


class RabbitClient(jclient.Client):
    """Durable-queue ops over AMQP publish/get/ack
    (rabbitmq.clj:135-175). basic.get + explicit ack after the value is
    in hand: a crash between get and ack re-delivers (at-least-once,
    what total-queue's :recovered accounting expects)."""

    def __init__(self, port: int = 5672, node: str | None = None,
                 timeout: float = 5.0):
        self.port = port
        self.node = node
        self.timeout = timeout
        self.conn = None
        self._declared = False

    def open(self, test, node):
        return RabbitClient(self.port, node, self.timeout)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import amqp
            host, port = resolve(self.node, self.port, test or {})
            self.conn = amqp.connect(host, port, timeout=self.timeout)
            # publisher confirms: enqueue ok must mean the broker has
            # the message (rabbitmq.clj publishes in confirm mode)
            self.conn.confirm_select()
            self._declared = False
        if not self._declared:
            self.conn.queue_declare(QUEUE, durable=True)
            self._declared = True

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def _dequeue1(self):
        """An error on the ack itself is indeterminate (the broker may
        have consumed the message) — AckIndeterminate makes callers
        report "info" rather than a definite fail."""
        got = self.conn.get(QUEUE)
        if got is None:
            return None
        tag, body = got
        try:
            self.conn.ack(tag)
        except (DriverError, OSError) as e:
            raise AckIndeterminate(int(body)) from e
        return int(body)

    def _drain(self, test, op):
        """Acked elements must survive a mid-drain error: once acked
        they're gone from the broker, so dropping them from the
        completion would read as data loss. Partial drains return ok
        with what was consumed; until_ok's other clients keep draining
        the remainder."""
        out = []
        try:
            while True:
                v = self._dequeue1()
                if v is None:
                    break
                out.append(v)
        except AckIndeterminate:
            self.close(test)   # acked prefix stays; unknown tail either
            # redelivers or counts lost (the reference's mode too)
        except (DBError, DriverError, OSError) as e:
            self.close(test)
            if not out:
                return {**op, "type": "fail", "error": str(e)[:160]}
        return {**op, "type": "ok", "value": out}

    def invoke(self, test, op):
        read_only = op["f"] == "dequeue"
        try:
            self._ensure_conn(test)
            if op["f"] == "enqueue":
                self.conn.publish(QUEUE, str(int(op["value"])).encode(),
                                  persistent=True)
                return {**op, "type": "ok"}
            if op["f"] == "dequeue":
                try:
                    v = self._dequeue1()
                except AckIndeterminate:
                    self.close(test)
                    return {**op, "type": "info",
                            "error": "ack-indeterminate"}
                if v is None:
                    return {**op, "type": "fail", "error": "empty"}
                return {**op, "type": "ok", "value": v}
            if op["f"] == "drain":
                return self._drain(test, op)
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except DBError as e:
            self.close(test)  # AMQP errors kill the channel
            return {**op, "type": "fail",
                    "error": f"amqp-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    return {"queue": lambda: queue_wl.test(opts.get("ops", 500))}


def rabbitmq_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wl = workloads(opts)["queue"]()
    test = {
        "name": "rabbitmq queue",
        "os": os_setup.debian(),
        "db": RabbitDB(),
        "client": opts.get("client") or RabbitClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": jchecker.compose({
            "queue": wl["checker"],
            "perf": jchecker.perf_checker(),
        }),
        # drain AFTER the time limit, with an explicit nemesis stop
        # first — a partition left up at the cutoff would wedge the
        # until-ok drain forever (the reference's std-gen shape)
        "generator": gen.phases(
            gen.time_limit(
                opts.get("time-limit", 60),
                gen.clients(wl["generator"],
                            nemesis_cycle(
                                opts.get("nemesis-interval", 10)))),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            wl["final_generator"]),
        "workload": "queue",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: rabbitmq_test(tmap),
                        name="rabbitmq", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
