"""Hazelcast suite.

Counterpart of hazelcast/src/jepsen/hazelcast.clj (821 LoC + the
SetUnionMergePolicy.java server extension): an embedded-jar server
started per node with a TCP/IP member list, driven over the Open
Client Protocol (drivers/hazelcast_proto.py) through the reference's
distinctive workload menu — locks (+ the no-quorum variant,
hazelcast.clj:412-449 & 652-677), queues with total-queue accounting
(270-296, 756), atomic-long unique ids (146-161, 766-770), and the
map/crdt-map set-union CAS workloads that exercise the shipped
SetUnionMergePolicy (453-509).
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis, os_setup
from ..checker import models
from ..control import util as cutil
from ..drivers import DriverError
from ..drivers import hazelcast_proto as hz
from ..workloads import queue as queue_wl
from . import base_opts, suite_test

DIR = "/opt/hazelcast"
VERSION = "3.10.3"
PIDFILE = f"{DIR}/hazelcast.pid"
LOGFILE = f"{DIR}/hazelcast.log"


class HazelcastDB(jdb.DB, jdb.LogFiles):
    """jar download + java -jar server with tcp-ip join config
    (install!/db, hazelcast.clj:69-110)."""

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        # jdk, not jre: the merge policy compiles on node (javac)
        sess.exec("apt-get", "install", "-y", "openjdk-8-jdk-headless")
        sess.exec("mkdir", "-p", DIR)
        url = (f"https://repo1.maven.org/maven2/com/hazelcast/hazelcast/"
               f"{self.version}/hazelcast-{self.version}.jar")
        sess.exec("sh", "-c",
                  f"test -f {DIR}/hazelcast.jar || "
                  f"wget -qO {DIR}/hazelcast.jar {url}")
        nodes = test.get("nodes", [node])
        members = "\n".join(
            f"          <member>{n}</member>" for n in nodes)
        cfg = ("<hazelcast xmlns=\"http://www.hazelcast.com/schema/"
               "config\">\n  <network>\n    <port>5701</port>\n"
               "    <join>\n      <multicast enabled=\"false\"/>\n"
               "      <tcp-ip enabled=\"true\">\n"
               f"{members}\n      </tcp-ip>\n    </join>\n"
               "  </network>\n"
               # split-brain heals by set union on the workload maps —
               # without this registration the policy is never invoked
               "  <map name=\"jepsen*\">\n"
               "    <merge-policy>jepsen.tpu.hazelcast."
               "SetUnionMergePolicy</merge-policy>\n"
               "  </map>\n</hazelcast>\n")
        sess.exec("sh", "-c",
                  f"cat > {DIR}/hazelcast.xml << 'EOF'\n{cfg}\nEOF")
        # server-side split-brain merge policy for the CRDT set
        # workload (resources/SetUnionMergePolicy.java) — compiled on
        # node like the reference's server extension
        import os.path as _p
        src = _p.join(_p.dirname(__file__), "resources",
                      "SetUnionMergePolicy.java")
        plain = control.current_session()
        plain.upload(src, "/tmp/SetUnionMergePolicy.java")
        sess.exec("mkdir", "-p",
                  f"{DIR}/classes/jepsen/tpu/hazelcast")
        # loud failure: a missing policy would silently change the
        # split-brain semantics the set workload tests
        sess.exec("sh", "-c",
                  f"cd /tmp && javac -cp {DIR}/hazelcast.jar "
                  f"-d {DIR}/classes SetUnionMergePolicy.java")
        cutil.start_daemon(
            sess, "java",
            f"-Dhazelcast.config={DIR}/hazelcast.xml",
            "-cp", f"{DIR}/hazelcast.jar:{DIR}/classes",
            "com.hazelcast.core.server.StartServer",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# wire clients (hazelcast.clj:146-161, 270-296, 412-449, 453-509)
# ---------------------------------------------------------------------------

class _HzClient(jclient.Client):
    """Shared connection plumbing: one HzConn per open, DriverError ->
    indeterminate for mutations (the reference's IOException handling,
    hazelcast.clj:439-446)."""

    port = 5701

    def __init__(self, conn: hz.HzConn | None = None,
                 port: int | None = None):
        self.conn = conn
        if port is not None:
            self.port = port

    def _open(self, node: str) -> hz.HzConn:
        return hz.HzConn(node, self.port)

    def open(self, test, node):
        c = type(self)(self._open(node), port=self.port)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


class LockClient(_HzClient):
    """ILock acquire/release (lock-client, hazelcast.clj:412-449):
    acquire = tryLock(5s) -> ok/fail; release = unlock, with
    not-lock-owner and quorum failures classified like the reference."""

    lock_name = "jepsen.lock"

    def __init__(self, conn=None, port=None, lock_name=None):
        super().__init__(conn, port)
        if lock_name is not None:
            self.lock_name = lock_name

    def open(self, test, node):
        c = type(self)(self._open(node), port=self.port,
                       lock_name=self.lock_name)
        return c

    def invoke(self, test, op):
        try:
            if op["f"] == "acquire":
                ok = self.conn.lock_try_lock(self.lock_name, 5000)
                return {**op, "type": "ok" if ok else "fail"}
            if op["f"] == "release":
                self.conn.lock_unlock(self.lock_name)
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": f"bad f {op['f']!r}"}
        except hz.HazelcastError as e:
            msg = str(e)
            if "not owner of the lock" in msg or \
                    "IllegalMonitorStateException" in msg:
                return {**op, "type": "fail", "error": "not-lock-owner"}
            if "QuorumException" in msg:
                return {**op, "type": "fail", "error": "quorum"}
            raise
        except DriverError as e:
            # acquire that never reached the cluster still may have:
            # indeterminate either way (a lost unlock matters too)
            return {**op, "type": "info", "error": str(e)[:120]}


class QueueClient(_HzClient):
    """IQueue enqueue/dequeue/drain with total-queue accounting
    (queue-client, hazelcast.clj:270-296)."""

    queue_name = "jepsen.queue"

    def invoke(self, test, op):
        try:
            if op["f"] == "enqueue":
                ok = self.conn.queue_offer(self.queue_name, op["value"])
                return {**op, "type": "ok" if ok else "fail"}
            if op["f"] == "dequeue":
                v = self.conn.queue_poll(self.queue_name)
                if v is None:
                    return {**op, "type": "fail", "error": "empty"}
                return {**op, "type": "ok", "value": v}
            if op["f"] == "drain":
                out = []
                while True:
                    v = self.conn.queue_poll(self.queue_name)
                    if v is None:
                        return {**op, "type": "ok", "value": out}
                    out.append(v)
            return {**op, "type": "fail", "error": f"bad f {op['f']!r}"}
        except DriverError as e:
            return {**op, "type": "info", "error": str(e)[:120]}


class AtomicLongIdClient(_HzClient):
    """IAtomicLong unique-id generation (atomic-long-id-client,
    hazelcast.clj:146-161)."""

    counter_name = "jepsen.atomic-long"

    def invoke(self, test, op):
        assert op["f"] == "generate", op
        try:
            v = self.conn.atomic_long_increment_and_get(self.counter_name)
            return {**op, "type": "ok", "value": v}
        except DriverError as e:
            return {**op, "type": "info", "error": str(e)[:120]}


class MapSetClient(_HzClient):
    """Grow-only set in an IMap under one key via CAS on a sorted long
    array (map-client, hazelcast.clj:453-491). With crdt=True the map
    is the one whose split-brain merges run the shipped
    SetUnionMergePolicy (the <map name="jepsen*"> registration in
    HazelcastDB.setup)."""

    def __init__(self, conn=None, port=None, crdt: bool = True):
        super().__init__(conn, port)
        self.crdt = crdt
        self.map_name = "jepsen.crdt-map" if crdt else "jepsen.map"

    def open(self, test, node):
        return type(self)(self._open(node), port=self.port,
                          crdt=self.crdt)

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                cur = self.conn.map_get(self.map_name, "hi")
                if cur is not None:
                    new = sorted(set(cur) | {op["value"]})
                    ok = self.conn.map_replace_if_same(
                        self.map_name, "hi", cur, new)
                    if ok:
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail", "error": "cas-failed"}
                prev = self.conn.map_put_if_absent(
                    self.map_name, "hi", [op["value"]])
                if prev is None:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-failed"}
            if op["f"] == "read":
                cur = self.conn.map_get(self.map_name, "hi")
                return {**op, "type": "ok",
                        "value": sorted(set(cur or []))}
            return {**op, "type": "fail", "error": f"bad f {op['f']!r}"}
        except DriverError as e:
            crash = "fail" if op["f"] == "read" else "info"
            return {**op, "type": crash, "error": str(e)[:120]}


# ---------------------------------------------------------------------------
# workloads (hazelcast.clj:652-777)
# ---------------------------------------------------------------------------

def _lock_workload(lock_name: str) -> dict:
    return {
        "client": LockClient(lock_name=lock_name),
        "generator": gen.each_thread(gen.stagger(0.1, gen.cycle(
            gen.Seq.of([{"type": "invoke", "f": "acquire"},
                        {"type": "invoke", "f": "release"}])))),
        "checker": jchecker.linearizable(models.mutex()),
    }


def _map_workload(crdt: bool) -> dict:
    def add(test=None, ctx=None):
        add.i += 1
        return {"type": "invoke", "f": "add", "value": add.i}
    add.i = -1
    return {
        "client": MapSetClient(crdt=crdt),
        "generator": gen.stagger(0.1, add),
        "final_generator": gen.each_thread(
            gen.once({"type": "invoke", "f": "read"})),
        "checker": jchecker.set_checker(),
    }


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    n = opts.get("queue-size", 500)
    return {
        "lock": lambda: _lock_workload("jepsen.lock"),
        "lock-no-quorum": lambda: _lock_workload("jepsen.lock.no-quorum"),
        "queue": lambda: {
            "client": QueueClient(),
            "generator": queue_wl.generator(n),
            "final_generator": queue_wl.final_generator(),
            "checker": jchecker.total_queue(),
        },
        "atomic-long-ids": lambda: {
            "client": AtomicLongIdClient(),
            "generator": gen.stagger(
                0.5, gen.repeat_gen({"type": "invoke", "f": "generate"})),
            "checker": jchecker.unique_ids(),
        },
        "map": lambda: _map_workload(crdt=False),
        "crdt-map": lambda: _map_workload(crdt=True),
    }


def hazelcast_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "crdt-map")
    return suite_test(
        "hazelcast", wname, opts, workloads(opts),
        db=HazelcastDB(opts.get("version", VERSION)),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: hazelcast_test(
            {**tmap, "workload": resolve_workload(args, tmap, "set")}),
        name="hazelcast",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
