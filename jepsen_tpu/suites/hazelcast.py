"""Hazelcast suite.

Counterpart of hazelcast/src/jepsen/hazelcast.clj (821 LoC + the
SetUnionMergePolicy.java server extension): an embedded-jar server
started per node with a TCP/IP member list, driven through locks,
queues, CRDT-ish sets and unique-id generators. The client protocol is
Hazelcast's JVM binary protocol — pluggable (pass ``client``);
install/daemon/workload wiring is complete.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, standard_workloads, suite_test

DIR = "/opt/hazelcast"
VERSION = "3.10.3"
PIDFILE = f"{DIR}/hazelcast.pid"
LOGFILE = f"{DIR}/hazelcast.log"


class HazelcastDB(jdb.DB, jdb.LogFiles):
    """jar download + java -jar server with tcp-ip join config
    (install!/db, hazelcast.clj:69-110)."""

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        # jdk, not jre: the merge policy compiles on node (javac)
        sess.exec("apt-get", "install", "-y", "openjdk-8-jdk-headless")
        sess.exec("mkdir", "-p", DIR)
        url = (f"https://repo1.maven.org/maven2/com/hazelcast/hazelcast/"
               f"{self.version}/hazelcast-{self.version}.jar")
        sess.exec("sh", "-c",
                  f"test -f {DIR}/hazelcast.jar || "
                  f"wget -qO {DIR}/hazelcast.jar {url}")
        nodes = test.get("nodes", [node])
        members = "\n".join(
            f"          <member>{n}</member>" for n in nodes)
        cfg = ("<hazelcast xmlns=\"http://www.hazelcast.com/schema/"
               "config\">\n  <network>\n    <port>5701</port>\n"
               "    <join>\n      <multicast enabled=\"false\"/>\n"
               "      <tcp-ip enabled=\"true\">\n"
               f"{members}\n      </tcp-ip>\n    </join>\n"
               "  </network>\n"
               # split-brain heals by set union on the workload maps —
               # without this registration the policy is never invoked
               "  <map name=\"jepsen*\">\n"
               "    <merge-policy>jepsen.tpu.hazelcast."
               "SetUnionMergePolicy</merge-policy>\n"
               "  </map>\n</hazelcast>\n")
        sess.exec("sh", "-c",
                  f"cat > {DIR}/hazelcast.xml << 'EOF'\n{cfg}\nEOF")
        # server-side split-brain merge policy for the CRDT set
        # workload (resources/SetUnionMergePolicy.java) — compiled on
        # node like the reference's server extension
        import os.path as _p
        src = _p.join(_p.dirname(__file__), "resources",
                      "SetUnionMergePolicy.java")
        plain = control.current_session()
        plain.upload(src, "/tmp/SetUnionMergePolicy.java")
        sess.exec("mkdir", "-p",
                  f"{DIR}/classes/jepsen/tpu/hazelcast")
        # loud failure: a missing policy would silently change the
        # split-brain semantics the set workload tests
        sess.exec("sh", "-c",
                  f"cd /tmp && javac -cp {DIR}/hazelcast.jar "
                  f"-d {DIR}/classes SetUnionMergePolicy.java")
        cutil.start_daemon(
            sess, "java",
            f"-Dhazelcast.config={DIR}/hazelcast.xml",
            "-cp", f"{DIR}/hazelcast.jar:{DIR}/classes",
            "com.hazelcast.core.server.StartServer",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    # hazelcast.clj's matrix: locks, queues, unique-ids, crdt sets —
    # the shared analogues:
    return {k: std[k] for k in ("set", "register", "monotonic")}


def hazelcast_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "set")
    return suite_test(
        "hazelcast", wname, opts, workloads(opts),
        db=HazelcastDB(opts.get("version", VERSION)),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: hazelcast_test(
            {**tmap, "workload": resolve_workload(args, tmap, "set")}),
        name="hazelcast",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
