"""MySQL Cluster (NDB) suite.

Counterpart of mysql-cluster/src/jepsen/mysql_cluster.clj (227 LoC):
management daemon on node 0, ndbd data nodes, mysqld SQL nodes, bank
workload over the mysql protocol.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, sql, standard_workloads, suite_test

DIR = "/opt/mysql-cluster"
VERSION = "7.4.8"


class MySQLClusterDB(jdb.DB, jdb.LogFiles):
    """ndb_mgmd (node 0) + ndbd + mysqld on each node."""

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://dev.mysql.com/get/Downloads/MySQL-Cluster-7.4/"
               f"mysql-cluster-gpl-{self.version}-linux-glibc2.5-"
               f"x86_64.tar.gz")
        cutil.install_archive(sess, url, DIR)
        nodes = test.get("nodes", [node])
        mgmd = nodes[0]
        if node == mgmd:
            ndbds = "\n".join(f"[ndbd]\nhostname={n}" for n in nodes)
            mysqlds = "\n".join(f"[mysqld]\nhostname={n}" for n in nodes)
            cfg = (f"[ndb_mgmd]\nhostname={mgmd}\ndatadir={DIR}/mgm\n"
                   f"[ndbd default]\nnoofreplicas=2\n"
                   f"datadir={DIR}/data\n{ndbds}\n{mysqlds}\n")
            sess.exec("mkdir", "-p", f"{DIR}/mgm")
            sess.exec("sh", "-c",
                      f"cat > {DIR}/config.ini << 'EOF'\n{cfg}\nEOF")
            cutil.start_daemon(
                sess, f"{DIR}/bin/ndb_mgmd", "--initial",
                "-f", f"{DIR}/config.ini",
                "--configdir", DIR,
                logfile=f"{DIR}/mgmd.log", pidfile=f"{DIR}/mgmd.pid",
                chdir=DIR)
        sess.exec("mkdir", "-p", f"{DIR}/data")
        cutil.start_daemon(
            sess, f"{DIR}/bin/ndbd",
            "--ndb-connectstring", mgmd,
            logfile=f"{DIR}/ndbd.log", pidfile=f"{DIR}/ndbd.pid",
            chdir=DIR)
        cutil.start_daemon(
            sess, f"{DIR}/bin/mysqld",
            "--ndbcluster",
            f"--ndb-connectstring={mgmd}",
            "--user=root",
            logfile=f"{DIR}/mysqld.log", pidfile=f"{DIR}/mysqld.pid",
            chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        for pid in ("mysqld.pid", "ndbd.pid", "mgmd.pid"):
            cutil.stop_daemon(sess, f"{DIR}/{pid}")
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [f"{DIR}/mgmd.log", f"{DIR}/ndbd.log", f"{DIR}/mysqld.log"]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {k: std[k] for k in ("bank", "set", "register")}


def default_client(workload: str, opts: dict):
    return sql.client_for(
        sql.MySQLDialect(port=3306, user="root", database="test"),
        workload, opts)


def mysql_cluster_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "bank")
    return suite_test(
        "mysql-cluster", wname, opts, workloads(opts),
        db=MySQLClusterDB(opts.get("version", VERSION)),
        client=opts.get("client") or default_client(wname, opts),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: mysql_cluster_test(
            {**tmap, "workload": resolve_workload(args, tmap, "bank")}),
        name="mysql-cluster",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
