"""Disque suite.

Counterpart of disque/src/jepsen/disque.clj: source install + cluster
meet, a queue workload over ADDJOB/GETJOB/ACKJOB (dequeue!,
disque.clj:194-231), checked by total-queue. The client speaks RESP
directly (drivers.resp) instead of jedisque.
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from ..drivers import DBError, DriverError
from ..workloads import queue as queue_wl
from . import base_opts, nemesis_cycle
from .sql import resolve

VERSION = "2a2e06c"
DIR = "/opt/disque"
BINARY = f"{DIR}/src/disque-server"
PIDFILE = f"{DIR}/disque.pid"
LOGFILE = f"{DIR}/disque.log"
PORT = 7711
QUEUE = "jepsen"


class DisqueDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """git clone + make + daemonize + CLUSTER MEET fan-in
    (install!/start!/join!, disque.clj:40-106); kill/pause fault
    protocols via SignalProcess."""

    process_pattern = "disque-server"

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start(self, sess, test, node):
        cutil.start_daemon(
            sess, BINARY,
            "--port", str(PORT),
            "--cluster-enabled", "yes",
            "--appendonly", "yes",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("sh", "-c",
                  f"test -d {DIR} || git clone "
                  f"https://github.com/antirez/disque {DIR}")
        sess.exec("sh", "-c",
                  f"cd {DIR} && git checkout {self.version} && make")
        self._start(sess, test, node)
        nodes = test.get("nodes", [])
        dummy = bool(test.get("ssh", {}).get("dummy"))
        if node == (nodes[0] if nodes else node) and not dummy:
            # cluster-meet goes over the wire protocol, not SSH
            # (join!, disque.clj:95-106) — skipped in dummy mode where
            # no server exists to dial.
            from ..drivers import resp
            import time
            time.sleep(2)
            c = resp.connect(node, PORT)
            for peer in nodes[1:]:
                c.command("CLUSTER", "MEET", peer, PORT)
            c.close()

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/appendonly.aof", f"{DIR}/nodes.conf")

    def log_files(self, test, node):
        return [LOGFILE]


class AckIndeterminate(Exception):
    """GETJOB delivered a job but the ACKJOB outcome is unknown."""

    def __init__(self, value):
        super().__init__("ack outcome unknown")
        self.value = value


class DisqueClient(jclient.Client):
    """Queue ops over ADDJOB/GETJOB/ACKJOB (disque.clj:140-231).
    GETJOB with a short timeout; jobs are acked after dequeue, so a
    crash between GET and ACK re-delivers (at-least-once — exactly what
    total-queue tolerates via its :recovered class)."""

    def __init__(self, port: int = PORT, node: str | None = None,
                 timeout: float = 5.0, getjob_timeout_ms: int = 100):
        self.port = port
        self.node = node
        self.timeout = timeout
        self.getjob_timeout_ms = getjob_timeout_ms
        self.conn = None

    def open(self, test, node):
        return DisqueClient(self.port, node, self.timeout,
                            self.getjob_timeout_ms)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import resp
            host, port = resolve(self.node, self.port, test or {})
            self.conn = resp.connect(host, port, self.timeout)

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def _dequeue1(self):
        """-> value | None (empty). An error on the ACKJOB itself is
        indeterminate — the server may have consumed the job — and
        surfaces as AckIndeterminate so callers report "info", not a
        definite fail (a false "fail" here reads as data loss to
        total-queue)."""
        jobs = self.conn.command(
            "GETJOB", "TIMEOUT", self.getjob_timeout_ms,
            "FROM", QUEUE)
        if not jobs:
            return None
        _q, job_id, body = jobs[0]
        try:
            self.conn.command("ACKJOB", job_id)
        except (DriverError, OSError) as e:
            raise AckIndeterminate(int(body)) from e
        return int(body)

    def _drain(self, test, op):
        """Acked elements survive a mid-drain error (they're gone from
        the server once ACKJOBed): partial drains return ok with what
        was consumed; the other clients' drains pick up the rest."""
        out = []
        try:
            while True:
                v = self._dequeue1()
                if v is None:
                    break
                out.append(v)
        except AckIndeterminate:
            # the unknown element either redelivers (another drain gets
            # it) or was consumed (the reference's failure mode too);
            # the definitively-acked prefix stays in the completion
            self.close(test)
        except (DBError, DriverError, OSError) as e:
            self.close(test)
            if not out:
                return {**op, "type": "fail", "error": str(e)[:160]}
        return {**op, "type": "ok", "value": out}

    def invoke(self, test, op):
        read_only = op["f"] == "dequeue"
        try:
            self._ensure_conn(test)
            if op["f"] == "enqueue":
                self.conn.command(
                    "ADDJOB", QUEUE, str(int(op["value"])), 5000,
                    "RETRY", 1)
                return {**op, "type": "ok"}
            if op["f"] == "dequeue":
                try:
                    v = self._dequeue1()
                except AckIndeterminate:
                    self.close(test)
                    return {**op, "type": "info",
                            "error": "ack-indeterminate"}
                if v is None:
                    return {**op, "type": "fail", "error": "empty"}
                return {**op, "type": "ok", "value": v}
            if op["f"] == "drain":
                return self._drain(test, op)
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except DBError as e:
            return {**op, "type": "fail",
                    "error": f"disque-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    return {"queue": lambda: queue_wl.test(opts.get("ops", 500))}


def disque_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wl = workloads(opts)["queue"]()
    test = {
        "name": "disque queue",
        "os": os_setup.debian(),
        "db": DisqueDB(opts.get("version", VERSION)),
        "client": opts.get("client") or DisqueClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": jchecker.compose({
            "queue": wl["checker"],
            "perf": jchecker.perf_checker(),
        }),
        # drain AFTER the time limit, with an explicit nemesis stop
        # first — a partition left up at the cutoff would wedge the
        # until-ok drain forever (std-gen, disque.clj:275-296)
        "generator": gen.phases(
            gen.time_limit(
                opts.get("time-limit", 60),
                gen.clients(wl["generator"],
                            nemesis_cycle(
                                opts.get("nemesis-interval", 10)))),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            wl["final_generator"]),
        "workload": "queue",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: disque_test(tmap),
                        name="disque", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
