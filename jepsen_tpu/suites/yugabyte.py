"""YugabyteDB suite — config #5's serializability sweep.

Counterpart of yugabyte/src/yugabyte (dual-API workload matrix,
yugabyte/core.clj:74-110; SURVEY.md §2.6): master + tserver daemons and
a matrix of counter-ish (monotonic), set, bank, long-fork, append, wr,
register workloads, optionally swept across both APIs the way the
reference sweeps YCQL/YSQL (the `api` opt tags the test; client adapters
are pluggable per API).
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, sql, standard_workloads, suite_test

VERSION = "1.3.1.0"
DIR = "/opt/yugabyte"

APIS = ("ysql", "ycql")


class YugaByteDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """yb-master + yb-tserver daemons (yugabyte/src/yugabyte/db.clj);
    whole-node kill/pause via SignalProcess."""

    process_pattern = f"{DIR}/bin"

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://downloads.yugabyte.com/"
               f"yugabyte-ce-{self.version}-linux.tar.gz")
        cutil.install_archive(sess, url, DIR)
        self._start(sess, test, node)

    def _start(self, sess, test, node):
        masters = ",".join(f"{n}:7100" for n in test.get("nodes", [])[:3])
        if node in test.get("nodes", [])[:3]:
            cutil.start_daemon(
                sess, f"{DIR}/bin/yb-master",
                "--master_addresses", masters,
                "--rpc_bind_addresses", f"{node}:7100",
                "--fs_data_dirs", f"{DIR}/data/master",
                logfile=f"{DIR}/master.log", pidfile=f"{DIR}/master.pid",
                chdir=DIR)
        cutil.start_daemon(
            sess, f"{DIR}/bin/yb-tserver",
            "--tserver_master_addrs", masters,
            "--rpc_bind_addresses", f"{node}:9100",
            "--fs_data_dirs", f"{DIR}/data/tserver",
            logfile=f"{DIR}/tserver.log", pidfile=f"{DIR}/tserver.pid",
            chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        for pid in ("tserver.pid", "master.pid"):
            cutil.stop_daemon(sess, f"{DIR}/{pid}")
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [f"{DIR}/master.log", f"{DIR}/tserver.log"]


def workloads(opts: dict | None = None, api: str = "ysql") -> dict:
    """The per-API workload matrix (yugabyte/core.clj:74-110): YSQL
    runs the full set; YCQL mirrors the reference's ycql/ namespace
    (bank, counter≈monotonic, long-fork, set, single-key-acid≈register)
    — append/wr need read-write txns YCQL blocks can't express."""
    std = standard_workloads(opts)
    names = ("register", "set", "bank", "long-fork", "append", "wr",
             "monotonic")
    if api == "ycql":
        names = ("register", "set", "bank", "long-fork", "monotonic")
    out = {k: std[k] for k in names}
    if api == "ycql":
        # YCQL transfers are blind server-side +/- in a txn block
        # (ycql/bank.clj:46-58): overdrafts are expected, only the
        # total is conserved.
        from ..workloads import bank as bank_wl

        def _pkg(t):
            return {"generator": t["generator"], "checker": t["checker"]}

        out["bank"] = lambda: _pkg(bank_wl.test(negative_balances=True))
    return out


#: nemesis-name -> constructor (run-jepsen.py's NEMESES sweep names;
#: the process-level ones target yb-tserver like the reference's)
NEMESES = {
    "none": jnemesis.noop,
    "partition": jnemesis.partition_random_halves,
    "partition-half": jnemesis.partition_halves,
    "partition-one": jnemesis.partition_random_node,
    "partition-ring": jnemesis.partition_majorities_ring,
    "pause-tserver": lambda: jnemesis.hammer_time("yb-tserver"),
    "pause-master": lambda: jnemesis.hammer_time("yb-master"),
}


def default_client(api: str, workload: str, opts: dict):
    """YSQL speaks pg-wire on 5433 (yugabyte/src/yugabyte/ysql).
    YCQL speaks the CQL binary protocol on 9042 (yugabyte/ycql)."""
    if api == "ycql":
        from . import ycql
        return ycql.client_for(workload, opts)
    return sql.client_for(
        sql.PGDialect(port=5433, user="yugabyte", database="yugabyte"),
        workload, opts)


def yugabyte_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    api = opts.get("api", "ysql")
    wname = opts.get("workload", "bank")
    nemesis_name = opts.get("nemesis", "partition")
    if nemesis_name not in NEMESES:
        raise ValueError(f"unknown nemesis {nemesis_name!r}; "
                         f"have {sorted(NEMESES)}")
    test = suite_test(
        f"yugabyte-{api}", wname, opts,
        workloads(opts, api),
        db=YugaByteDB(opts.get("version", VERSION)),
        client=opts.get("client") or default_client(api, wname, opts),
        nemesis=NEMESES[nemesis_name](),
        os_setup=os_setup.debian())
    test["api"] = api
    test["nemesis-name"] = nemesis_name
    return test


def all_tests(opts: dict | None = None) -> list[dict]:
    """The full api × workload sweep (yugabyte/core.clj:74-110,
    run-jepsen.py's sweep)."""
    opts = base_opts(**(opts or {}))
    return [yugabyte_test({**opts, "api": api, "workload": w})
            for api in APIS for w in sorted(workloads(opts, api))]


def main(argv=None) -> int:
    from . import resolve_workload

    def opt_fn(p):
        p.add_argument("--workload", default=None,
                       choices=sorted(workloads()))
        p.add_argument("--api", default=None, choices=APIS)
        p.add_argument("--nemesis", default="partition",
                       choices=sorted(NEMESES))

    return jcli.run_cli(
        lambda tmap, args: yugabyte_test(
            {**tmap, "workload": resolve_workload(args, tmap, "bank"),
             "api": (getattr(args, "api", None) or tmap.get("api")
                     or "ysql"),
             "nemesis": getattr(args, "nemesis", "partition")}),
        name="yugabyte", opt_fn=opt_fn,
        tests_fn=lambda tmap, args: [
            yugabyte_test({**tmap, "api": api, "workload": w,
                           "nemesis": getattr(args, "nemesis",
                                              "partition")})
            for api in ([args.api] if getattr(args, "api", None)
                        else APIS)
            for w in ([args.workload] if getattr(args, "workload", None)
                      else sorted(workloads(tmap, api)))],
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
