"""TiDB suite — config #4 of the north star.

Counterpart of tidb/src/tidb (workload registry with option sweeps,
tidb/core.clj:32-100; SURVEY.md §2.6): the pd / tikv / tidb daemon trio
installed from the release tarball, and a workload matrix of bank,
long-fork, append/wr (Elle), register, set, sequential, monotonic.
SQL access is driver-pluggable as in the cockroach suite.
"""

from __future__ import annotations

import random

from .. import checker as jchecker
from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, sql, standard_workloads, suite_test

VERSION = "v3.0.3"
DIR = "/opt/tidb"
LOGDIR = f"{DIR}/logs"

class TiDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """pd + tikv + tidb daemons (tidb/src/tidb/db.clj's install);
    whole-node kill/pause across all three via SignalProcess (the
    pattern matches every binary under the install dir)."""

    process_pattern = f"{DIR}/bin"

    def __init__(self, version: str = VERSION):
        self.version = version

    def _pd_cluster(self, test) -> str:
        return ",".join(f"{n}=http://{n}:2380"
                        for n in test.get("nodes", []))

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://download.pingcap.org/"
               f"tidb-{self.version}-linux-amd64.tar.gz")
        cutil.install_archive(sess, url, DIR)
        sess.exec("mkdir", "-p", LOGDIR)
        self._start(sess, test, node)

    def _start(self, sess, test, node):
        cutil.start_daemon(
            sess, f"{DIR}/bin/pd-server",
            "--name", node,
            "--client-urls", f"http://{node}:2379",
            "--peer-urls", f"http://{node}:2380",
            "--initial-cluster", self._pd_cluster(test),
            logfile=f"{LOGDIR}/pd.log", pidfile=f"{DIR}/pd.pid", chdir=DIR)
        pds = ",".join(f"{n}:2379" for n in test.get("nodes", []))
        cutil.start_daemon(
            sess, f"{DIR}/bin/tikv-server",
            "--pd", pds,
            "--addr", f"{node}:20160",
            "--data-dir", f"{DIR}/tikv",
            logfile=f"{LOGDIR}/tikv.log", pidfile=f"{DIR}/tikv.pid",
            chdir=DIR)
        cutil.start_daemon(
            sess, f"{DIR}/bin/tidb-server",
            "--store", "tikv",
            "--path", pds,
            "--host", node,
            logfile=f"{LOGDIR}/tidb.log", pidfile=f"{DIR}/tidb.pid",
            chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        for pid in ("tidb.pid", "tikv.pid", "pd.pid"):
            cutil.stop_daemon(sess, f"{DIR}/{pid}")
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [f"{LOGDIR}/pd.log", f"{LOGDIR}/tikv.log",
                f"{LOGDIR}/tidb.log"]


class TableChecker(jchecker.Checker):
    """tidb/table.clj:69-77: an insert that bounced off a 'missing'
    table is the anomaly — the generator only ever inserts into tables
    whose creation was already acknowledged."""

    def check(self, test, history, opts):
        bad = [op for op in history
               if op.get("type") == "fail"
               and op.get("error") == "doesnt-exist"]
        return {"valid?": not bad, "errors": bad[:16],
                "error-count": len(bad)}


def table_workload(opts: dict | None = None) -> dict:
    """tidb/table.clj:54-67,79-85: repeatedly create fresh tables;
    80% of the time insert into the last table whose create-table op
    completed ok. DDL that isn't visible to subsequent inserts shows
    up as `doesnt-exist` failures."""
    state = {"last": None, "next": 0}

    def emit(test=None, ctx=None):
        if state["last"] is not None and random.random() < 0.8:
            return {"type": "invoke", "f": "insert",
                    "value": [state["last"], 0]}
        state["next"] += 1
        return {"type": "invoke", "f": "create-table",
                "value": state["next"]}

    def watch(this, test, ctx, event):
        # the reference bumps last-created-table as each create COMMITS
        # (table.clj:28-32's swap! in invoke!)
        if (event.get("type") == "ok"
                and event.get("f") == "create-table"):
            v = int(event["value"])
            state["last"] = v if state["last"] is None \
                else max(state["last"], v)
        return this

    return {"generator": gen.on_update(watch, emit),
            "checker": jchecker.compose({"table": TableChecker()})}


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    out = {k: std[k] for k in
           ("bank", "long-fork", "append", "wr", "register", "set",
            "sequential", "monotonic")}
    out["table"] = lambda: table_workload(opts)
    return out


#: Per-workload option sweeps (tidb/core.clj:47-79 workload-options):
#: each option maps to every value the sweep should try.
WORKLOAD_OPTIONS: dict[str, dict[str, list]] = {
    "append":     {"auto-retry": [True, False],
                   "auto-retry-limit": [10, 0],
                   "read-lock": [None, "FOR UPDATE"]},
    "bank":       {"auto-retry": [True, False],
                   "auto-retry-limit": [10, 0],
                   "update-in-place": [True, False],
                   "read-lock": [None, "FOR UPDATE"]},
    "long-fork":  {"auto-retry": [True, False],
                   "auto-retry-limit": [10, 0]},
    "monotonic":  {"auto-retry": [True, False],
                   "auto-retry-limit": [10, 0]},
    "register":   {"auto-retry": [True, False],
                   "auto-retry-limit": [10, 0],
                   "read-lock": [None, "FOR UPDATE"]},
    "wr":         {"auto-retry": [True, False],
                   "auto-retry-limit": [10, 0],
                   "read-lock": [None, "FOR UPDATE"]},
    "set":        {"auto-retry": [True, False],
                   "auto-retry-limit": [10, 0]},
    "sequential": {"auto-retry": [True, False],
                   "auto-retry-limit": [10, 0]},
    "table":      {},   # table.clj has no option knobs
}


def expected_to_pass_options() -> dict:
    """Sweep restricted to combos expected valid: auto-retry off
    (tidb/core.clj:81-86 workload-options-expected-to-pass)."""
    return {w: {**o, "auto-retry": [False], "auto-retry-limit": [0]}
            for w, o in WORKLOAD_OPTIONS.items()}


def quick_options() -> dict:
    """One representative combo per workload: defaults only, no read
    locks (tidb/core.clj:88-105 quick-workload-options). update-in-place
    stays True — the safe server-side-arithmetic default; False is the
    deliberately lost-update-prone sweep variant."""
    return {w: {k: [None] if k == "read-lock"
                else [True] if k == "update-in-place"
                else [v[0]]
                for k, v in o.items()}
            for w, o in WORKLOAD_OPTIONS.items()}


def option_combos(options: dict[str, list]) -> list[dict]:
    """Cartesian product of one workload's option values."""
    import itertools
    keys = sorted(options)
    return [dict(zip(keys, vals))
            for vals in itertools.product(*(options[k] for k in keys))]


def _session_stmts(combo: dict) -> list[str]:
    """The tidb session knobs an option combo sets on every connection
    (tidb/sql.clj's set-session-variables)."""
    stmts = []
    if "auto-retry" in combo:
        on = bool(combo["auto-retry"])
        stmts.append(f"SET @@tidb_disable_txn_auto_retry = "
                     f"{0 if on else 1}")
    if combo.get("auto-retry-limit") is not None:
        stmts.append(f"SET @@tidb_retry_limit = "
                     f"{int(combo['auto-retry-limit'])}")
    return stmts


def default_client(workload: str, opts: dict):
    """mysql-protocol client on tidb-server's port (the reference
    drives tidb through jdbc/mysql, tidb/src/tidb/sql.clj). Workload
    options become session variables + client knobs."""
    combo = opts.get("workload-options") or {}
    dialect = sql.MySQLDialect(port=4000, user="root", database="test",
                               session_stmts=_session_stmts(combo))
    sql_opts = {"read_lock": combo.get("read-lock"),
                "update_in_place": combo.get("update-in-place", True)}
    return sql.client_for(dialect, workload,
                          {**opts, "sql-opts": sql_opts})


def tidb_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "append")
    test = suite_test(
        "tidb", wname, opts, workloads(opts),
        db=TiDB(opts.get("version", VERSION)),
        client=opts.get("client") or default_client(wname, opts),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())
    combo = opts.get("workload-options")
    if combo:
        flavor = " ".join(f"{k}={v}" for k, v in sorted(combo.items()))
        test["name"] = f"tidb {wname} {flavor}"
        test["workload-options"] = combo
    return test


def all_tests(opts: dict | None = None, tier: str = "full") -> list[dict]:
    """The workload x option sweep (tidb/core.clj's test-all): tier
    "full" | "expected" | "quick" picks the option matrix."""
    opts = dict(opts or {})
    matrix = {"full": WORKLOAD_OPTIONS,
              "expected": expected_to_pass_options(),
              "quick": quick_options()}[tier]
    wanted = ([opts["workload"]] if opts.get("workload")
              else sorted(workloads()))
    return [tidb_test({**opts, "workload": w, "workload-options": combo})
            for w in wanted
            for combo in option_combos(matrix.get(w, {}))]


def main(argv=None) -> int:
    from . import resolve_workload

    def opt_fn(p):
        p.add_argument("--workload", default=None,
                       choices=sorted(workloads()))
        p.add_argument("--sweep", default="quick",
                       choices=("full", "expected", "quick"),
                       help="option-matrix tier for test-all")

    return jcli.run_cli(
        lambda tmap, args: tidb_test(
            {**tmap, "workload": resolve_workload(args, tmap, "append")}),
        name="tidb",
        opt_fn=opt_fn,
        tests_fn=lambda tmap, args: all_tests(
            {**tmap, "workload": getattr(args, "workload", None)},
            tier=getattr(args, "sweep", "quick")),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
