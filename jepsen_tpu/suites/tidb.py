"""TiDB suite — config #4 of the north star.

Counterpart of tidb/src/tidb (workload registry with option sweeps,
tidb/core.clj:32-100; SURVEY.md §2.6): the pd / tikv / tidb daemon trio
installed from the release tarball, and a workload matrix of bank,
long-fork, append/wr (Elle), register, set, sequential, monotonic.
SQL access is driver-pluggable as in the cockroach suite.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, sql, standard_workloads, suite_test

VERSION = "v3.0.3"
DIR = "/opt/tidb"
LOGDIR = f"{DIR}/logs"

class TiDB(jdb.DB, jdb.LogFiles):
    """pd + tikv + tidb daemons (tidb/src/tidb/db.clj's install)."""

    def __init__(self, version: str = VERSION):
        self.version = version

    def _pd_cluster(self, test) -> str:
        return ",".join(f"{n}=http://{n}:2380"
                        for n in test.get("nodes", []))

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://download.pingcap.org/"
               f"tidb-{self.version}-linux-amd64.tar.gz")
        cutil.install_archive(sess, url, DIR)
        sess.exec("mkdir", "-p", LOGDIR)
        cutil.start_daemon(
            sess, f"{DIR}/bin/pd-server",
            "--name", node,
            "--client-urls", f"http://{node}:2379",
            "--peer-urls", f"http://{node}:2380",
            "--initial-cluster", self._pd_cluster(test),
            logfile=f"{LOGDIR}/pd.log", pidfile=f"{DIR}/pd.pid", chdir=DIR)
        pds = ",".join(f"{n}:2379" for n in test.get("nodes", []))
        cutil.start_daemon(
            sess, f"{DIR}/bin/tikv-server",
            "--pd", pds,
            "--addr", f"{node}:20160",
            "--data-dir", f"{DIR}/tikv",
            logfile=f"{LOGDIR}/tikv.log", pidfile=f"{DIR}/tikv.pid",
            chdir=DIR)
        cutil.start_daemon(
            sess, f"{DIR}/bin/tidb-server",
            "--store", "tikv",
            "--path", pds,
            "--host", node,
            logfile=f"{LOGDIR}/tidb.log", pidfile=f"{DIR}/tidb.pid",
            chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        for pid in ("tidb.pid", "tikv.pid", "pd.pid"):
            cutil.stop_daemon(sess, f"{DIR}/{pid}")
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [f"{LOGDIR}/pd.log", f"{LOGDIR}/tikv.log",
                f"{LOGDIR}/tidb.log"]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {k: std[k] for k in
            ("bank", "long-fork", "append", "wr", "register", "set",
             "sequential", "monotonic")}


def default_client(workload: str, opts: dict):
    """mysql-protocol client on tidb-server's port (the reference
    drives tidb through jdbc/mysql, tidb/src/tidb/sql.clj)."""
    return sql.client_for(
        sql.MySQLDialect(port=4000, user="root", database="test"),
        workload, opts)


def tidb_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "append")
    return suite_test(
        "tidb", wname, opts, workloads(opts),
        db=TiDB(opts.get("version", VERSION)),
        client=opts.get("client") or default_client(wname, opts),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: tidb_test(
            {**tmap, "workload": resolve_workload(args, tmap, "append")}),
        name="tidb",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        tests_fn=lambda tmap, args: [
            tidb_test({**tmap, "workload": w})
            for w in ([args.workload] if getattr(
                args, "workload", None) else sorted(workloads()))],
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
