"""Galera (MariaDB + wsrep) suite.

Counterpart of galera/src/jepsen/galera.clj: apt-installed MariaDB
with a wsrep cluster address (configure!, galera.clj:64-74), driven
over the mysql protocol. Workload matrix mirrors the reference's sets
+ bank tests plus the shared SQL extras.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..workloads import dirty_reads
from . import base_opts, sql, standard_workloads, suite_test

LOGFILE = "/var/log/mysql/error.log"


class GaleraDB(jdb.DB, jdb.LogFiles):
    """apt install mariadb-galera + wsrep cluster bootstrap
    (install!/configure!/setup-db!, galera.clj:34-100)."""

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "mariadb-server", "galera-4")
        nodes = test.get("nodes", [node])
        cluster = ",".join(nodes)
        cfg = "\n".join([
            "[galera]",
            "wsrep_on=ON",
            "wsrep_provider=/usr/lib/galera/libgalera_smm.so",
            f"wsrep_cluster_address=gcomm://{cluster}",
            f"wsrep_node_address={node}",
            f"wsrep_node_name={node}",
            "binlog_format=row",
            "default_storage_engine=InnoDB",
            "innodb_autoinc_lock_mode=2",
            "bind-address=0.0.0.0",
        ])
        sess.exec("sh", "-c",
                  f"cat > /etc/mysql/conf.d/galera.cnf << 'EOF'\n{cfg}\nEOF")
        if node == nodes[0]:
            sess.exec("galera_new_cluster")
        else:
            sess.exec("service", "mysql", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "mysql", "stop")

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    # galera.clj ships sets + bank; register/monotonic ride along from
    # the shared matrix. dirty-reads is the suite's signature check
    # (galera/src/jepsen/galera/dirty_reads.clj:1-120).
    out = {k: std[k] for k in ("set", "bank", "register", "monotonic")}
    out["dirty-reads"] = dirty_reads.workload
    return out


def default_client(workload: str, opts: dict):
    sql_opts = opts.get("sql-opts")
    if workload == "dirty-reads":
        # A healthy cluster rarely aborts on its own; deliberate
        # rollbacks keep the checker's failed-write pool non-empty.
        # Merge per-key so unrelated sql-opts don't void the default.
        sql_opts = {"abort_prob": 0.05, **(sql_opts or {})}
    return sql.client_for(
        sql.MySQLDialect(port=3306, user="root", database="test"),
        workload, {**opts, "sql-opts": sql_opts})


def galera_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "bank")
    return suite_test(
        "galera", wname, opts, workloads(opts),
        db=GaleraDB(),
        client=opts.get("client") or default_client(wname, opts),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: galera_test(
            {**tmap, "workload": resolve_workload(args, tmap, "bank")}),
        name="galera",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
