-------------------------------- MODULE aerospike --------------------------------
(* Model spec accompanying the aerospike suite, playing the role the     *)
(* reference's TLA+ spec plays for its suite: an abstract model of a     *)
(* partition-replicated CAS register under node failure and partition,   *)
(* checked against the linearizability property the suite's register     *)
(* workload tests empirically. The interesting (falsifiable) claim: with *)
(* ReplicationFactor < Quorum during a partition, both sides can accept  *)
(* writes for the same key and an acknowledged write is lost on heal —   *)
(* exactly the anomaly the empirical suite hunts.                        *)

EXTENDS Naturals, FiniteSets

CONSTANTS
  Nodes,            \* the cluster
  Values,           \* writable register values
  ReplicationFactor \* copies per key

ASSUME ReplicationFactor \in 1..Cardinality(Nodes)

VARIABLES
  partition,   \* a set of nodes isolated from the rest ({} = healthy)
  replicas,    \* node -> register value it holds (or NoVal)
  acked,       \* set of values whose writes were acknowledged
  observed     \* set of values any read has returned

NoVal == CHOOSE v : v \notin Values

Side(n) == IF n \in partition THEN partition ELSE Nodes \ partition

\* A write lands on ReplicationFactor nodes reachable from some
\* coordinator's side; it is acknowledged iff enough replicas are
\* reachable there.
WriteTo(side, v) ==
  /\ Cardinality(side) >= ReplicationFactor
  /\ \E targets \in SUBSET side :
       /\ Cardinality(targets) = ReplicationFactor
       /\ replicas' = [n \in Nodes |->
                        IF n \in targets THEN v ELSE replicas[n]]
       /\ acked' = acked \cup {v}
       /\ UNCHANGED <<partition, observed>>

Write(v) ==
  \/ WriteTo(Nodes \ partition, v)
  \/ partition /= {} /\ WriteTo(partition, v)

Read(n) ==
  /\ replicas[n] /= NoVal
  /\ observed' = observed \cup {replicas[n]}
  /\ UNCHANGED <<partition, replicas, acked>>

Partition(p) ==
  /\ partition = {}
  /\ p /= {} /\ p /= Nodes
  /\ partition' = p
  /\ UNCHANGED <<replicas, acked, observed>>

\* Healing reconciles divergent replicas by picking ONE side's value
\* per node pair — the other side's acknowledged writes are gone.
Heal ==
  /\ partition /= {}
  /\ \E keep \in {partition, Nodes \ partition} :
       \E v \in {replicas[n] : n \in keep} :
         replicas' = [n \in Nodes |-> v]
  /\ partition' = {}
  /\ UNCHANGED <<acked, observed>>

Init ==
  /\ partition = {}
  /\ replicas = [n \in Nodes |-> NoVal]
  /\ acked = {}
  /\ observed = {}

Next ==
  \/ \E v \in Values : Write(v)
  \/ \E n \in Nodes : Read(n)
  \/ \E p \in SUBSET Nodes : Partition(p)
  \/ Heal

Spec == Init /\ [][Next]_<<partition, replicas, acked, observed>>

--------------------------------------------------------------------------------
(* Properties                                                            *)

\* Durability: once healed, every acknowledged write survives on some
\* replica. FALSE when ReplicationFactor <= Cardinality(Nodes) - Quorum:
\* TLC produces the lost-write trace the suite reproduces empirically.
NoLostAckedWrites ==
  partition = {} =>
    \A v \in acked : \E n \in Nodes : replicas[n] = v

\* Reads never observe unacknowledged (phantom) values.
NoPhantomReads == observed \subseteq acked

================================================================================
