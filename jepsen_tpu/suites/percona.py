"""Percona XtraDB Cluster suite.

Counterpart of percona/src/jepsen/percona.clj (bank + sets over a
galera-based XtraDB cluster, mysql protocol). Same shape as the galera
suite with Percona's packages and bootstrap command.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..workloads import dirty_reads
from . import base_opts, sql, standard_workloads, suite_test

LOGFILE = "/var/log/mysql/error.log"


class PerconaDB(jdb.DB, jdb.LogFiles):
    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "percona-xtradb-cluster-57")
        nodes = test.get("nodes", [node])
        cluster = ",".join(nodes)
        cfg = "\n".join([
            "[mysqld]",
            "wsrep_provider=/usr/lib/galera3/libgalera_smm.so",
            f"wsrep_cluster_address=gcomm://{cluster}",
            f"wsrep_node_address={node}",
            "wsrep_sst_method=xtrabackup-v2",
            "pxc_strict_mode=ENFORCING",
            "binlog_format=ROW",
            "default_storage_engine=InnoDB",
            "innodb_autoinc_lock_mode=2",
        ])
        sess.exec("sh", "-c",
                  f"cat > /etc/mysql/percona-xtradb-cluster.conf.d/"
                  f"jepsen.cnf << 'EOF'\n{cfg}\nEOF")
        if node == nodes[0]:
            sess.exec("sh", "-c",
                      "systemctl start mysql@bootstrap || "
                      "/etc/init.d/mysql bootstrap-pxc")
        else:
            sess.exec("service", "mysql", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "mysql", "stop")

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    # dirty-reads is the suite's signature check
    # (percona/src/jepsen/percona/dirty_reads.clj:1-120).
    out = {k: std[k] for k in ("set", "bank", "register", "sequential")}
    out["dirty-reads"] = dirty_reads.workload
    return out


def default_client(workload: str, opts: dict):
    sql_opts = opts.get("sql-opts")
    if workload == "dirty-reads":
        # merge per-key so unrelated sql-opts don't void the default
        sql_opts = {"abort_prob": 0.05, **(sql_opts or {})}
    return sql.client_for(
        sql.MySQLDialect(port=3306, user="root", database="test"),
        workload, {**opts, "sql-opts": sql_opts})


def percona_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "bank")
    return suite_test(
        "percona", wname, opts, workloads(opts),
        db=PerconaDB(),
        client=opts.get("client") or default_client(wname, opts),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: percona_test(
            {**tmap, "workload": resolve_workload(args, tmap, "bank")}),
        name="percona",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
