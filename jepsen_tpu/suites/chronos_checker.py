"""Chronos job-schedule checker.

Counterpart of chronos/src/jepsen/chronos/checker.clj (321 LoC): given
the jobs a test scheduled (each with a start, repeat count, interval,
epsilon tolerance and run duration) and the runs a final read collected
off the nodes, verify that every *target* invocation window — the k-th
scheduled occurrence, widened by the job's epsilon plus a small global
forgiveness — was satisfied by a distinct completed run.

Where the reference poses the target→run assignment as a finite-domain
constraint program (checker.clj:116-189, loco `$distinct`/`$nth`), this
solves the same problem directly: targets of one job are uniform-width
windows sorted by start time, so the bipartite "each window needs its
own run-start point" matching is solved exactly by a single
earliest-window-first / earliest-feasible-run greedy pass (the classic
exchange argument for interval point-matching — any satisfiable
instance is satisfied by the greedy choice, in O(targets + runs)
instead of a CP solve).

Times are plain epoch seconds (floats); ISO-8601 strings (including
the comma-fraction variant `date -Ins` emits, checker.clj's
parse-file-time counterpart lives in the suite) are normalized on the
way in.
"""

from __future__ import annotations

from datetime import datetime

from .. import checker as jchecker
from ..util import iso_to_epoch

# The reference lets chronos miss deadlines by a few extra seconds
# (checker.clj:26-28).
EPSILON_FORGIVENESS = 5.0


def parse_time(t) -> float | None:
    """Normalize a timestamp to epoch seconds. Accepts numbers,
    datetimes, and ISO-8601 strings — `date -u -Ins` separates
    fractional seconds with a comma, which is valid ISO but worth
    normalizing before parsing (chronos.clj:143-149). NAIVE datetimes
    are interpreted as LOCAL time: the one naive producer is core.py's
    `start-time` (datetime.now().strftime), and shifting it to UTC
    would skew read_time by the host's UTC offset against the jobs'
    true-epoch start values."""
    if t is None:
        return None
    if isinstance(t, (int, float)):
        return float(t)
    if isinstance(t, datetime):
        return t.timestamp()       # naive -> local, aware -> exact
    return iso_to_epoch(str(t))    # full-precision (date -Ins is ns)


def job_targets(read_time: float, job: dict) -> list[tuple[float, float]]:
    """[start, stop] windows for every target that MUST have begun by
    the time of the read (checker.clj:30-47): a job may start up to
    epsilon late and takes duration to finish, so only targets before
    `read_time - epsilon - duration` are required; each window extends
    epsilon + forgiveness past its target time."""
    start = parse_time(job["start"])
    interval = float(job["interval"])
    epsilon = float(job["epsilon"])
    duration = float(job["duration"])
    finish = read_time - epsilon - duration
    out = []
    for k in range(int(job["count"])):
        t = start + k * interval
        if not t < finish:
            break
        out.append((t, t + epsilon + EPSILON_FORGIVENESS))
    return out


def split_complete(runs: list[dict]) -> tuple[list[dict], list[dict]]:
    """Partition runs into (completed, incomplete), each sorted by
    start (checker.clj:59-76). A run without an :end began but never
    finished — it can't satisfy a target."""
    runs = [r for r in runs if r.get("start") is not None]
    complete = sorted((r for r in runs if r.get("end") is not None),
                      key=lambda r: parse_time(r["start"]))
    incomplete = sorted((r for r in runs if r.get("end") is None),
                        key=lambda r: parse_time(r["start"]))
    return complete, incomplete


def match_targets(targets: list[tuple[float, float]],
                  runs: list[dict]) -> list[dict | None]:
    """Assign each target window a DISTINCT completed run whose start
    falls inside it, maximizing the number of satisfied targets.

    Both lists are sorted by start and all windows share one width, so
    greedy earliest-window-first taking the earliest feasible run is
    optimal: any run skipped here (started before the current window)
    can never satisfy a later window either. Equivalent to the
    reference's `$distinct` + `$nth` constraint solve
    (checker.clj:146-168) on satisfiable instances, and to its
    disjoint-job-solution riffle (checker.clj:78-114) on overlap-free
    failures."""
    out: list[dict | None] = []
    i = 0
    for (t0, t1) in targets:
        while i < len(runs) and parse_time(runs[i]["start"]) < t0:
            i += 1          # too early for this and every later window
        if i < len(runs) and parse_time(runs[i]["start"]) <= t1:
            out.append(runs[i])
            i += 1
        else:
            out.append(None)
    return out


def job_solution(read_time: float, job: dict,
                 runs: list[dict] | None) -> dict:
    """Solve one job (checker.clj:116-189). Returns
    {valid?, job, solution: [(target, run-or-None)...],
     extra: completed-but-unneeded runs, complete, incomplete}."""
    targets = job_targets(read_time, job)
    complete, incomplete = split_complete(runs or [])
    assigned = match_targets(targets, complete)
    used = {id(r) for r in assigned if r is not None}
    return {
        "valid?": all(r is not None for r in assigned),
        "job": job,
        "solution": list(zip(targets, assigned)),
        "extra": [r for r in complete if id(r) not in used],
        "complete": complete,
        "incomplete": incomplete,
    }


def solution(read_time: float, jobs: list[dict],
             runs: list[dict]) -> dict:
    """All jobs (checker.clj:191-213): group runs by job name, solve
    each, valid? iff every job is."""
    # Runs whose file couldn't be parsed to a job name OR a start
    # timestamp (partial writes, stray files): can't match/classify —
    # surface them rather than silently dropping corruption evidence.
    by_name: dict = {}
    unparseable = []
    for r in runs:
        if r.get("name") is None or r.get("start") is None:
            unparseable.append(r)
        else:
            by_name.setdefault(r["name"], []).append(r)
    solns = {j["name"]: job_solution(read_time, j,
                                     by_name.get(j["name"]))
             for j in jobs}
    return {
        "valid?": all(s["valid?"] for s in solns.values()),
        "jobs": solns,
        "extra": [r for s in solns.values() for r in s["extra"]],
        "incomplete": [r for s in solns.values()
                       for r in s["incomplete"]],
        "unparseable": unparseable,
        "read-time": read_time,
    }


def plot_solution(soln: dict, start_time: float, path) -> None:
    """chronos.png (checker.clj:223-292): one row per job; target
    windows shaded green when satisfied / red when missed, run spans
    drawn as solid bars (green complete, red incomplete)."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib.patches import Rectangle

    green, red = "#00AB01", "#AB0001"
    fig, ax = plt.subplots(figsize=(9, 4))
    rows = sorted(soln["jobs"])
    for y, name in enumerate(rows, start=1):
        s = soln["jobs"][name]
        for (t0, t1), run in s["solution"]:
            ax.add_patch(Rectangle(
                (t0 - start_time, y + 0.1), t1 - t0, 0.8,
                facecolor=green if run is not None else red, alpha=0.3,
                edgecolor="none"))
        for run in s["complete"] + s["incomplete"]:
            r0 = parse_time(run["start"]) - start_time
            r1 = max(r0 + 1, (parse_time(run["end"]) - start_time)
                     if run.get("end") is not None else r0 + 1)
            ax.add_patch(Rectangle(
                (r0, y + 0.4), r1 - r0, 0.2,
                facecolor=green if run.get("end") is not None else red,
                edgecolor="none"))
    ax.set_xlim(0, max(1.0, soln["read-time"] - start_time))
    ax.set_ylim(0, len(rows) + 1)
    ax.set_ylabel("Job")
    ax.set_xlabel("Time (s)")
    fig.savefig(path, dpi=96)
    plt.close(fig)


class ChronosChecker(jchecker.Checker):
    """The suite checker (checker.clj:294-321): read-time comes from
    the final read's INVOKE (runs observed by the read can't postdate
    its issue), runs from the read's :ok value, jobs from every
    successful add-job."""

    def check(self, test, history, opts):
        read_inv = next((o for o in reversed(history)
                         if o.get("type") == "invoke"
                         and o.get("f") == "read"), None)
        read_ok = next((o for o in reversed(history)
                        if o.get("type") == "ok"
                        and o.get("f") == "read"), None)
        if read_ok is None or read_inv is None:
            return {"valid?": "unknown", "error": "no final read"}
        start_time = parse_time(test.get("start-time")) or 0.0
        read_time = start_time + read_inv.get("time", 0) / 1e9
        jobs = [o["value"] for o in history
                if o.get("type") == "ok" and o.get("f") == "add-job"]
        soln = solution(read_time, jobs, read_ok["value"] or [])
        try:
            from ..checker.perf import store_path
            p = store_path(test, opts, "chronos.png")
            if p is not None:
                plot_solution(soln, start_time, p)
        except Exception:
            pass                       # the verdict never dies on a plot
        # summary counts ride along for the one-line report
        missed = sum(1 for s in soln["jobs"].values()
                     for (_, r) in s["solution"] if r is None)
        soln["target-count"] = sum(len(s["solution"])
                                   for s in soln["jobs"].values())
        soln["missed-count"] = missed
        soln["extra-count"] = len(soln["extra"])
        return soln
