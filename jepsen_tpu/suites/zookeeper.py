"""ZooKeeper suite.

Counterpart of zookeeper/src/jepsen/zookeeper.clj (137 LoC, the
smallest real suite): apt-installed ZooKeeper with per-node myid +
zoo.cfg (zookeeper.clj:20-60), a CAS register per key over znode
versions, and a per-key linearizability check. The client speaks the
jute wire protocol directly (drivers.zk) instead of avout:
getData returns the znode version, setData with that version is the
CAS.
"""

from __future__ import annotations


from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent, nemesis as jnemesis, os_setup
from ..checker import models
from ..drivers import DBError, DriverError
from . import base_opts, nemesis_cycle
from .sql import resolve

VERSION = "3.4.13-2"
CFG = "/etc/zookeeper/conf"


def node_ids(test: dict) -> dict:
    return {n: i for i, n in enumerate(test.get("nodes", []))}


def zoo_cfg(test: dict) -> str:
    """zoo.cfg body (zoo-cfg-servers, zookeeper.clj:32-38)."""
    lines = [
        "tickTime=2000", "initLimit=10", "syncLimit=5",
        "dataDir=/var/lib/zookeeper", "clientPort=2181",
    ]
    lines += [f"server.{i}={n}:2888:3888"
              for n, i in node_ids(test).items()]
    return "\n".join(lines)


class ZookeeperDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """apt install + myid + zoo.cfg + service restart
    (db, zookeeper.clj:40-66); kill/pause fault protocols via
    SignalProcess."""

    process_pattern = "zookeeper"

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start(self, sess, test, node):
        sess.exec("service", "zookeeper", "start")

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y",
                  f"zookeeper={self.version}",
                  f"zookeeper-bin={self.version}",
                  f"zookeeperd={self.version}")
        sess.exec("sh", "-c",
                  f"echo {node_ids(test)[node]} > {CFG}/myid")
        sess.exec("sh", "-c",
                  f"cat > {CFG}/zoo.cfg << 'EOF'\n{zoo_cfg(test)}\nEOF")
        sess.exec("service", "zookeeper", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "zookeeper", "stop")
        sess.exec("rm", "-rf", "/var/lib/zookeeper/version-2")

    def log_files(self, test, node):
        return ["/var/log/zookeeper/zookeeper.log"]


class ZKClient(jclient.Client):
    """CAS register per key over znode data versions."""

    def __init__(self, port: int = 2181, node: str | None = None,
                 timeout: float = 5.0):
        self.port = port
        self.node = node
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        return ZKClient(self.port, node, self.timeout)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import zk
            host, port = resolve(self.node, self.port, test or {})
            self.conn = zk.connect(host, port, self.timeout)

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def _path(self, k) -> str:
        return f"/jepsen-r{k}"

    def invoke(self, test, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        read_only = op["f"] == "read"
        try:
            self._ensure_conn(test)
            c = self.conn
            if op["f"] == "read":
                try:
                    data, _stat = c.get_data(self._path(k))
                except DBError as e:
                    if e.code == "no-node":
                        return {**op, "type": "ok", "value": lift(None)}
                    raise
                return {**op, "type": "ok",
                        "value": lift(int(data) if data else None)}
            if op["f"] == "write":
                try:
                    c.set_data(self._path(k), str(int(val)).encode())
                except DBError as e:
                    if e.code != "no-node":
                        raise
                    try:
                        c.create(self._path(k), str(int(val)).encode())
                    except DBError as e2:
                        if e2.code != "node-exists":
                            raise
                        c.set_data(self._path(k), str(int(val)).encode())
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = val
                try:
                    data, stat = c.get_data(self._path(k))
                except DBError as e:
                    if e.code == "no-node":
                        return {**op, "type": "fail", "error": "no-node"}
                    raise
                cur = int(data) if data else None
                if cur != old:
                    return {**op, "type": "fail", "error": "precondition"}
                try:
                    # version-guarded write: the znode CAS primitive
                    c.set_data(self._path(k), str(int(new)).encode(),
                               version=stat.version)
                except DBError as e:
                    if e.code == "bad-version":
                        return {**op, "type": "fail",
                                "error": "bad-version"}
                    raise
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except DBError as e:
            return {**op, "type": "fail",
                    "error": f"zk-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}


def workloads(opts: dict | None = None) -> dict:
    from ..workloads.register import rand_op

    def register():
        return {
            "generator": independent.concurrent_generator(
                2, range(10_000),
                lambda k: gen.limit(100, rand_op)),
            "checker": independent.checker(jchecker.compose({
                "timeline": jchecker.timeline_checker(),
                "linear": jchecker.linearizable(models.cas_register()),
            })),
        }

    return {"register": register}


def zookeeper_test(opts: dict | None = None) -> dict:
    """Full test map (zk-test, zookeeper.clj:120-137)."""
    opts = base_opts(**(opts or {}))
    wl = workloads(opts)["register"]()
    test = {
        "name": "zookeeper register",
        "os": os_setup.debian(),
        "db": ZookeeperDB(opts.get("version", VERSION)),
        "client": opts.get("client") or ZKClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": wl["checker"],
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(wl["generator"],
                        nemesis_cycle(opts.get("nemesis-interval", 10)))),
        "workload": "register",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: zookeeper_test(tmap),
                        name="zookeeper", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
