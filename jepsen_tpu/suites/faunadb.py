"""FaunaDB suite.

Counterpart of faunadb/src/jepsen/faunadb/ (3,605 LoC, the largest
remaining reference suite): deb-installed FaunaDB with a
log-replicated cluster, driven over its HTTP+JSON query API with
secret-key auth (the reference's JVM driver is the same HTTP endpoint,
client.clj:36-60). FaunaClient speaks the FQL wire-JSON protocol via
drivers.fauna_http and maps the register (register.clj:31-62), set
(set.clj:35-60), bank (bank.clj:80-140), monotonic and g2 families;
pass ``client`` to substitute your own.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import independent
from .. import nemesis as jnemesis, os_setup
from ..drivers import DBError, DriverError
from . import base_opts, standard_workloads, suite_test

LOGFILE = "/var/log/faunadb/core.log"


class FaunaDB(jdb.DB, jdb.LogFiles):
    """deb install + faunadb.yml with the replica topology
    (faunadb/src/jepsen/faunadb/auto.clj's install!/configure!)."""

    def __init__(self, version: str = "2.5.5"):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("sh", "-c",
                  "wget -qO- https://repo.fauna.com/faunadb-gpg-public"
                  ".key | apt-key add -")
        sess.exec("sh", "-c",
                  'echo "deb [arch=amd64] https://repo.fauna.com/debian'
                  ' stable non-free" > /etc/apt/sources.list.d/'
                  'faunadb.list')
        sess.exec("apt-get", "update")
        sess.exec("apt-get", "install", "-y",
                  f"faunadb={self.version}")
        nodes = test.get("nodes", [node])
        cfg = "\n".join([
            "auth_root_key: secret",
            f"network_broadcast_address: {node}",
            f"network_host_id: {node}",
            "network_listen_address: 0.0.0.0",
            "storage_data_path: /var/lib/faunadb",
            "log_path: /var/log/faunadb",
        ])
        sess.exec("sh", "-c",
                  f"cat > /etc/faunadb.yml << 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "faunadb", "restart")
        if node == nodes[0]:
            sess.exec_ok("faunadb-admin", "init")
        else:
            sess.exec_ok("faunadb-admin", "join", nodes[0])

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "faunadb", "stop")
        sess.exec("rm", "-rf", "/var/lib/faunadb")

    def log_files(self, test, node):
        return [LOGFILE]


class FaunaClient(jclient.Client):
    """Workload ops over the FQL wire protocol (drivers.fauna_http).

    Each mode mirrors its reference client: register
    (register.clj:31-62, CAS via Let/Select/If over data.register),
    set (set.clj:35-60, class + all-elements index, reads paginate the
    index), bank (bank.clj:80-140, transfer aborts when the balance
    would go negative), monotonic (counter via Add), g2
    (g2.clj, predicate emptiness check then insert)."""

    PORT = 8443

    def __init__(self, mode: str = "register", accounts: list | None = None,
                 total: int = 100, node: str | None = None,
                 timeout: float = 10.0):
        self.mode = mode
        self.accounts = accounts if accounts is not None else list(range(8))
        self.total = total
        self.node = node
        self.timeout = timeout
        self.conn = None
        self._setup_done = False

    def open(self, test, node):
        return FaunaClient(self.mode, self.accounts, self.total, node,
                           self.timeout)

    def _ensure_conn(self, test):
        from ..drivers import fauna_http as q
        from .sql import resolve
        if self.conn is None:
            host, port = resolve(self.node, self.PORT, test or {})
            # register/set read through /linearized like the reference
            self.conn = q.connect(
                host, port, linearized=self.mode in ("register", "set"),
                timeout=self.timeout)
        if not self._setup_done:
            self._setup(q)
            self._setup_done = True

    def _upsert_class(self, q, name: str):
        self.conn.query(q.if_(q.exists(q.class_(name)), None,
                              q.create_class({"name": name})))

    def _setup(self, q):
        if self.mode in ("register", "monotonic"):
            self._upsert_class(q, "test")
        elif self.mode == "set":
            self._upsert_class(q, "elements")
            self.conn.query(q.if_(
                q.exists(q.index("all-elements")), None,
                q.create_index({
                    "name": "all-elements",
                    "source": q.class_("elements"),
                    "active": True,
                    "values": [{"field": ["data", "value"]}]})))
        elif self.mode == "bank":
            self._upsert_class(q, "accounts")
            for i, a in enumerate(self.accounts):
                ref = q.ref_(q.class_("accounts"), a)
                bal = self.total if i == 0 else 0
                self.conn.query(q.when(
                    q.not_(q.exists(ref)),
                    q.create(ref, {"data": {"balance": bal}})))
        elif self.mode == "g2":
            for name in ("a", "b"):
                self._upsert_class(q, name)
                self.conn.query(q.if_(
                    q.exists(q.index(f"{name}-by-key")), None,
                    q.create_index({
                        "name": f"{name}-by-key",
                        "source": q.class_(name),
                        "active": True,
                        "terms": [{"field": ["data", "key"]}]})))

    def close(self, test):
        self.conn = None

    def invoke(self, test, op):
        read_only = op.get("f") == "read"
        try:
            self._ensure_conn(test)
            return self._dispatch(op)
        except DBError as e:
            return {**op, "type": "fail",
                    "error": f"fauna-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    def _dispatch(self, op):
        from ..drivers import fauna_http as q
        f = op["f"]
        v = op.get("value")
        if self.mode == "register":
            k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
            lift = (lambda x: independent.tuple_(k, x)) \
                if independent.is_tuple(v) else (lambda x: x)
            ref = q.ref_(q.class_("test"), k)
            if f == "read":
                res = self.conn.query(q.if_(q.exists(ref), q.get_(ref)))
                reg = (res or {}).get("data", {}).get("register") \
                    if isinstance(res, dict) else None
                return {**op, "type": "ok", "value": lift(reg)}
            if f == "write":
                self.conn.query(q.if_(
                    q.exists(ref),
                    q.update(ref, {"data": {"register": val}}),
                    q.create(ref, {"data": {"register": val}})))
                return {**op, "type": "ok"}
            if f == "cas":
                old, new = val
                res = self.conn.query(q.if_(
                    q.exists(ref),
                    q.let({"reg": q.select(["data", "register"],
                                           q.get_(ref))},
                          q.if_(q.equals(old, q.var("reg")),
                                q.update(ref,
                                         {"data": {"register": new}}),
                                False)),
                    False))
                return {**op, "type": "ok" if res else "fail"}
        elif self.mode == "set":
            if f == "add":
                self.conn.query(q.create(q.ref_(q.class_("elements"), v),
                                         {"data": {"value": v}}))
                return {**op, "type": "ok"}
            if f == "read":
                vals = self.conn.query_all(q.match(q.index("all-elements")))
                return {**op, "type": "ok", "value": set(vals)}
        elif self.mode == "bank":
            cls = q.class_("accounts")
            if f == "read":
                res = self.conn.query([
                    q.when(q.exists(q.ref_(cls, a)),
                           [a, q.select(["data", "balance"],
                                        q.get_(q.ref_(cls, a)))])
                    for a in self.accounts])
                return {**op, "type": "ok",
                        "value": {p[0]: p[1] for p in res if p}}
            if f == "transfer":
                frm, to, amt = v["from"], v["to"], v["amount"]
                try:
                    self.conn.query(q.let(
                        {"a": q.subtract(
                            q.select(["data", "balance"],
                                     q.get_(q.ref_(cls, frm))), amt)},
                        q.if_(q.lt(q.var("a"), 0),
                              q.abort("balance would go negative"),
                              q.do(
                                  q.update(q.ref_(cls, frm),
                                           {"data": {"balance":
                                                     q.var("a")}}),
                                  q.update(q.ref_(cls, to),
                                           {"data": {"balance": q.add(
                                               q.select(
                                                   ["data", "balance"],
                                                   q.get_(q.ref_(cls,
                                                                 to))),
                                               amt)}})))))
                    return {**op, "type": "ok"}
                except DBError as e:
                    if "would go negative" in e.message:
                        return {**op, "type": "fail", "error": "negative"}
                    raise
        elif self.mode == "monotonic":
            ref = q.ref_(q.class_("test"), 0)
            if f == "read":
                res = self.conn.query(q.if_(
                    q.exists(ref),
                    q.select(["data", "value"], q.get_(ref)), 0))
                return {**op, "type": "ok", "value": res}
            if f == "inc":
                res = self.conn.query(q.if_(
                    q.exists(ref),
                    q.select(["data", "value"], q.update(
                        ref, {"data": {"value": q.add(
                            q.select(["data", "value"], q.get_(ref)),
                            1)}})),
                    q.select(["data", "value"],
                             q.create(ref, {"data": {"value": 1}}))))
                return {**op, "type": "ok", "value": res}
        elif self.mode == "g2":
            if f == "insert":
                k, ids = (v.key, v.value) if independent.is_tuple(v) \
                    else (v[0], v[1])
                a_id, b_id = ids
                tbl = "a" if a_id is not None else "b"
                the_id = a_id if a_id is not None else b_id
                empty = lambda n: q.equals(  # noqa: E731
                    q.select(["data"],
                             q.paginate(q.match(q.index(f"{n}-by-key"),
                                                k), size=1)), [])
                res = self.conn.query(q.if_(
                    q.and_(empty("a"), empty("b")),
                    q.do(q.create(q.ref_(q.class_(tbl), the_id),
                                  {"data": {"key": k, "id": the_id}}),
                         True),
                    False))
                return {**op, "type": "ok" if res else "fail"}
        return {**op, "type": "fail", "error": f"unknown f {f!r}"}


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    out = {}
    for k in ("register", "set", "bank", "monotonic", "g2"):
        def make(name=k):
            pkg = dict(std[name]())
            pkg.setdefault("client", FaunaClient(mode=name))
            return pkg
        out[k] = make
    return out


def faunadb_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    return suite_test(
        "faunadb", wname, opts, workloads(opts),
        db=FaunaDB(opts.get("version", "2.5.5")),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: faunadb_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="faunadb",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
