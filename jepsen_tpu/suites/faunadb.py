"""FaunaDB suite.

Counterpart of faunadb/src/jepsen/faunadb/ (3,605 LoC, the largest
remaining reference suite): deb-installed FaunaDB with a
log-replicated cluster, driven over its HTTP+JSON query API with
secret-key auth. The workload matrix maps the reference's
register/set/bank/monotonic/pages families onto the shared library;
FQL query construction is client-pluggable (pass ``client``) — the
install/cluster/workload wiring is complete.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from . import base_opts, standard_workloads, suite_test

LOGFILE = "/var/log/faunadb/core.log"


class FaunaDB(jdb.DB, jdb.LogFiles):
    """deb install + faunadb.yml with the replica topology
    (faunadb/src/jepsen/faunadb/auto.clj's install!/configure!)."""

    def __init__(self, version: str = "2.5.5"):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("sh", "-c",
                  "wget -qO- https://repo.fauna.com/faunadb-gpg-public"
                  ".key | apt-key add -")
        sess.exec("sh", "-c",
                  'echo "deb [arch=amd64] https://repo.fauna.com/debian'
                  ' stable non-free" > /etc/apt/sources.list.d/'
                  'faunadb.list')
        sess.exec("apt-get", "update")
        sess.exec("apt-get", "install", "-y",
                  f"faunadb={self.version}")
        nodes = test.get("nodes", [node])
        cfg = "\n".join([
            "auth_root_key: secret",
            f"network_broadcast_address: {node}",
            f"network_host_id: {node}",
            "network_listen_address: 0.0.0.0",
            "storage_data_path: /var/lib/faunadb",
            "log_path: /var/log/faunadb",
        ])
        sess.exec("sh", "-c",
                  f"cat > /etc/faunadb.yml << 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "faunadb", "restart")
        if node == nodes[0]:
            sess.exec_ok("faunadb-admin", "init")
        else:
            sess.exec_ok("faunadb-admin", "join", nodes[0])

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "faunadb", "stop")
        sess.exec("rm", "-rf", "/var/lib/faunadb")

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {k: std[k] for k in
            ("register", "set", "bank", "monotonic", "g2")}


def faunadb_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    return suite_test(
        "faunadb", wname, opts, workloads(opts),
        db=FaunaDB(opts.get("version", "2.5.5")),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: faunadb_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="faunadb",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
