"""FaunaDB suite.

Counterpart of faunadb/src/jepsen/faunadb/ (3,605 LoC, the largest
remaining reference suite): deb-installed FaunaDB with a
log-replicated cluster, driven over its HTTP+JSON query API with
secret-key auth (the reference's JVM driver is the same HTTP endpoint,
client.clj:36-60). FaunaClient speaks the FQL wire-JSON protocol via
drivers.fauna_http and maps the register (register.clj:31-62), set
(set.clj:35-60), bank (bank.clj:80-140), monotonic, g2, pages
(pages.clj — pagination isolation of grouped inserts) and
multimonotonic (multimonotonic.clj — increment-only registers with
timestamp-order and read-skew checkers) families; pass ``client`` to
substitute your own. opts {"nemesis": "topology"} swaps the partition
nemesis for the cluster-membership TopologyNemesis (topology.clj).
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent
from .. import nemesis as jnemesis, os_setup
from ..drivers import DBError, DriverError
from . import base_opts, standard_workloads, suite_test

LOGFILE = "/var/log/faunadb/core.log"


class FaunaDB(jdb.DB, jdb.LogFiles):
    """deb install + faunadb.yml with the replica topology
    (faunadb/src/jepsen/faunadb/auto.clj's install!/configure!)."""

    def __init__(self, version: str = "2.5.5"):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("sh", "-c",
                  "wget -qO- https://repo.fauna.com/faunadb-gpg-public"
                  ".key | apt-key add -")
        sess.exec("sh", "-c",
                  'echo "deb [arch=amd64] https://repo.fauna.com/debian'
                  ' stable non-free" > /etc/apt/sources.list.d/'
                  'faunadb.list')
        sess.exec("apt-get", "update")
        sess.exec("apt-get", "install", "-y",
                  f"faunadb={self.version}")
        nodes = test.get("nodes", [node])
        cfg = "\n".join([
            "auth_root_key: secret",
            f"network_broadcast_address: {node}",
            f"network_host_id: {node}",
            "network_listen_address: 0.0.0.0",
            "storage_data_path: /var/lib/faunadb",
            "log_path: /var/log/faunadb",
        ])
        sess.exec("sh", "-c",
                  f"cat > /etc/faunadb.yml << 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "faunadb", "restart")
        if node == nodes[0]:
            sess.exec_ok("faunadb-admin", "init")
        else:
            sess.exec_ok("faunadb-admin", "join", nodes[0])

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "faunadb", "stop")
        sess.exec("rm", "-rf", "/var/lib/faunadb")

    def log_files(self, test, node):
        return [LOGFILE]


class FaunaClient(jclient.Client):
    """Workload ops over the FQL wire protocol (drivers.fauna_http).

    Each mode mirrors its reference client: register
    (register.clj:31-62, CAS via Let/Select/If over data.register),
    set (set.clj:35-60, class + all-elements index, reads paginate the
    index), bank (bank.clj:80-140, transfer aborts when the balance
    would go negative), monotonic (counter via Add), g2
    (g2.clj, predicate emptiness check then insert)."""

    PORT = 8443

    def __init__(self, mode: str = "register", accounts: list | None = None,
                 total: int = 100, node: str | None = None,
                 timeout: float = 10.0, naive_reads: bool = False):
        self.mode = mode
        self.accounts = accounts if accounts is not None else list(range(8))
        self.total = total
        self.node = node
        self.timeout = timeout
        self.naive_reads = naive_reads  # pages: per-page transactions
        self.conn = None
        self._setup_done = False

    def open(self, test, node):
        return FaunaClient(self.mode, self.accounts, self.total, node,
                           self.timeout, self.naive_reads)

    def _ensure_conn(self, test):
        from ..drivers import fauna_http as q
        from .sql import resolve
        if self.conn is None:
            host, port = resolve(self.node, self.PORT, test or {})
            # register/set read through /linearized like the reference
            self.conn = q.connect(
                host, port, linearized=self.mode in ("register", "set"),
                timeout=self.timeout)
        if not self._setup_done:
            self._setup(q)
            self._setup_done = True

    def _upsert_class(self, q, name: str):
        self.conn.query(q.if_(q.exists(q.class_(name)), None,
                              q.create_class({"name": name})))

    def _setup(self, q):
        if self.mode in ("register", "monotonic"):
            self._upsert_class(q, "test")
        elif self.mode == "set":
            self._upsert_class(q, "elements")
            self.conn.query(q.if_(
                q.exists(q.index("all-elements")), None,
                q.create_index({
                    "name": "all-elements",
                    "source": q.class_("elements"),
                    "active": True,
                    "values": [{"field": ["data", "value"]}]})))
        elif self.mode == "bank":
            self._upsert_class(q, "accounts")
            for i, a in enumerate(self.accounts):
                ref = q.ref_(q.class_("accounts"), a)
                bal = self.total if i == 0 else 0
                self.conn.query(q.when(
                    q.not_(q.exists(ref)),
                    q.create(ref, {"data": {"balance": bal}})))
        elif self.mode == "g2":
            for name in ("a", "b"):
                self._upsert_class(q, name)
                self.conn.query(q.if_(
                    q.exists(q.index(f"{name}-by-key")), None,
                    q.create_index({
                        "name": f"{name}-by-key",
                        "source": q.class_(name),
                        "active": True,
                        "terms": [{"field": ["data", "key"]}]})))
        elif self.mode == "pages":
            self._upsert_class(q, "elements")
            self.conn.query(q.if_(
                q.exists(q.index("elements-by-key")), None,
                q.create_index({
                    "name": "elements-by-key",
                    "source": q.class_("elements"),
                    "active": True,
                    "terms": [{"field": ["data", "key"]}],
                    "values": [{"field": ["data", "value"]}]})))
        elif self.mode == "multimonotonic":
            self._upsert_class(q, "registers")
        elif self.mode == "internal":
            self._upsert_class(q, "cats")
            self.conn.query(q.if_(
                q.exists(q.index("cats_by_type")), None,
                q.create_index({
                    "name": "cats_by_type",
                    "source": q.class_("cats"),
                    "active": True,
                    "terms": [{"field": ["data", "type"]}],
                    "values": [{"field": ["ref"]},
                               {"field": ["data", "name"]}]})))

    # -- internal-consistency mode (faunadb/internal.clj) ------------------

    @staticmethod
    def _cats_pairs(q, typ):
        """[[ref, name], ...] for cats of `typ` via the index."""
        return q.select(["data"], q.paginate(
            q.match(q.index("cats_by_type"), typ), size=1024))

    @classmethod
    def _cats_names(cls, q, typ):
        return q.map_(q.lambda_(["r", "name"], q.var("name")),
                      cls._cats_pairs(q, typ))

    @classmethod
    def _delete_by_type(cls, q, typ):
        refs = q.map_(q.lambda_(["r", "name"], q.var("r")),
                      cls._cats_pairs(q, typ))
        return q.foreach(
            q.lambda_("r", q.when(q.exists(q.var("r")),
                                  q.delete(q.var("r")))), refs)

    def _internal_dispatch(self, q, op, f, v):
        """internal.clj:69-133: one txn creates a cat and reads the
        index before/after INSIDE the txn, through three differently-
        shaped queries (let bindings, object literal, array literal) —
        all must observe the txn's own effects identically."""
        create = q.create(q.class_("cats"),
                          {"data": {"type": "tabby", "name": v}})
        match = self._cats_names(q, "tabby")
        if f == "reset":
            self.conn.query(q.do(self._delete_by_type(q, "tabby"),
                                 self._delete_by_type(q, "calico")))
            return {**op, "type": "ok"}
        if f == "create-tabby-let":
            res = self.conn.query(q.let(
                {"t": q.time("now")},
                q.let({"tabbies_0": q.at(q.var("t"), match),
                       "tabby": create,
                       "tabbies_1": q.at(q.var("t"), match)},
                      # reversed key order vs the bindings, like the
                      # reference, so we check let scoping not literals
                      {"tabbies-1": q.var("tabbies_1"),
                       "tabby": q.var("tabby"),
                       "tabbies-0": q.var("tabbies_0")})))
        elif f == "create-tabby-obj":
            r = self.conn.query({"c": match, "a": create, "b": match})
            res = {"tabbies-0": r["c"], "tabby": r["a"],
                   "tabbies-1": r["b"]}
        elif f == "create-tabby-arr":
            r = self.conn.query([match, create, match])
            res = {"tabbies-0": r[0], "tabby": r[1], "tabbies-1": r[2]}
        elif f == "change-type":
            refs1 = q.map_(q.lambda_(["r", "name"], q.var("r")),
                           q.select(["data"], q.paginate(
                               q.match(q.index("cats_by_type"),
                                       "tabby"), size=1)))
            r = self.conn.query([
                q.let({"rs": refs1},
                      q.when(q.not_(q.equals(q.var("rs"), [])),
                             q.update(q.select([0], q.var("rs")),
                                      {"data": {"type": "calico"}}))),
                match, self._cats_names(q, "calico")])
            return {**op, "type": "ok",
                    "value": {"cat": r[0], "tabbies": r[1],
                              "calicos": r[2]}}
        else:
            return {**op, "type": "fail", "error": f"unknown f {f!r}"}
        return {**op, "type": "ok", "value": res}

    def close(self, test):
        self.conn = None

    def invoke(self, test, op):
        read_only = op.get("f") == "read"
        try:
            self._ensure_conn(test)
            return self._dispatch(op)
        except DBError as e:
            return {**op, "type": "fail",
                    "error": f"fauna-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    def _dispatch(self, op):
        from ..drivers import fauna_http as q
        f = op["f"]
        v = op.get("value")
        if self.mode == "register":
            k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
            lift = (lambda x: independent.tuple_(k, x)) \
                if independent.is_tuple(v) else (lambda x: x)
            ref = q.ref_(q.class_("test"), k)
            if f == "read":
                res = self.conn.query(q.if_(q.exists(ref), q.get_(ref)))
                reg = (res or {}).get("data", {}).get("register") \
                    if isinstance(res, dict) else None
                return {**op, "type": "ok", "value": lift(reg)}
            if f == "write":
                self.conn.query(q.if_(
                    q.exists(ref),
                    q.update(ref, {"data": {"register": val}}),
                    q.create(ref, {"data": {"register": val}})))
                return {**op, "type": "ok"}
            if f == "cas":
                old, new = val
                res = self.conn.query(q.if_(
                    q.exists(ref),
                    q.let({"reg": q.select(["data", "register"],
                                           q.get_(ref))},
                          q.if_(q.equals(old, q.var("reg")),
                                q.update(ref,
                                         {"data": {"register": new}}),
                                False)),
                    False))
                return {**op, "type": "ok" if res else "fail"}
        elif self.mode == "set":
            if f == "add":
                self.conn.query(q.create(q.ref_(q.class_("elements"), v),
                                         {"data": {"value": v}}))
                return {**op, "type": "ok"}
            if f == "read":
                vals = self.conn.query_all(q.match(q.index("all-elements")))
                return {**op, "type": "ok", "value": set(vals)}
        elif self.mode == "bank":
            cls = q.class_("accounts")
            if f == "read":
                res = self.conn.query([
                    q.when(q.exists(q.ref_(cls, a)),
                           [a, q.select(["data", "balance"],
                                        q.get_(q.ref_(cls, a)))])
                    for a in self.accounts])
                return {**op, "type": "ok",
                        "value": {p[0]: p[1] for p in res if p}}
            if f == "transfer":
                frm, to, amt = v["from"], v["to"], v["amount"]
                try:
                    self.conn.query(q.let(
                        {"a": q.subtract(
                            q.select(["data", "balance"],
                                     q.get_(q.ref_(cls, frm))), amt)},
                        q.if_(q.lt(q.var("a"), 0),
                              q.abort("balance would go negative"),
                              q.do(
                                  q.update(q.ref_(cls, frm),
                                           {"data": {"balance":
                                                     q.var("a")}}),
                                  q.update(q.ref_(cls, to),
                                           {"data": {"balance": q.add(
                                               q.select(
                                                   ["data", "balance"],
                                                   q.get_(q.ref_(cls,
                                                                 to))),
                                               amt)}})))))
                    return {**op, "type": "ok"}
                except DBError as e:
                    if "would go negative" in e.message:
                        return {**op, "type": "fail", "error": "negative"}
                    raise
        elif self.mode == "monotonic":
            ref = q.ref_(q.class_("test"), 0)
            if f == "read":
                res = self.conn.query(q.if_(
                    q.exists(ref),
                    q.select(["data", "value"], q.get_(ref)), 0))
                return {**op, "type": "ok", "value": res}
            if f == "inc":
                res = self.conn.query(q.if_(
                    q.exists(ref),
                    q.select(["data", "value"], q.update(
                        ref, {"data": {"value": q.add(
                            q.select(["data", "value"], q.get_(ref)),
                            1)}})),
                    q.select(["data", "value"],
                             q.create(ref, {"data": {"value": 1}}))))
                return {**op, "type": "ok", "value": res}
        elif self.mode == "pages":
            # pages.clj:31-66: groups of elements created in ONE txn;
            # concurrent paginated reads of the key's whole index — for
            # every element of a group, the rest must appear too.
            k, val = (v.key, v.value) if independent.is_tuple(v) \
                else (0, v)
            if f == "add":
                self.conn.query(q.do(*[
                    q.create(q.ref_(q.class_("elements"), f"{k}:{e}"),
                             {"data": {"key": k, "value": e}})
                    for e in val]))
                return {**op, "type": "ok"}
            if f == "read":
                q_all = (self.conn.query_all_naive if self.naive_reads
                         else self.conn.query_all)
                vals = q_all(q.match(q.index("elements-by-key"), k))
                return {**op, "type": "ok",
                        "value": independent.tuple_(k, list(vals))}
        elif self.mode == "multimonotonic":
            # multimonotonic.clj:76-107: blind single-txn writes of
            # {key: value} maps; reads pin a timestamp and fetch a
            # subset of registers with their instance ts.
            if f == "write":
                self.conn.query([
                    q.if_(q.exists(q.ref_(q.class_("registers"), k)),
                          q.update(q.ref_(q.class_("registers"), k),
                                   {"data": {"value": val}}),
                          q.create(q.ref_(q.class_("registers"), k),
                                   {"data": {"value": val}}))
                    for k, val in v.items()])
                return {**op, "type": "ok"}
            if f == "read":
                ks = list(v)
                res = self.conn.query(
                    [q.time("now")] +
                    [q.when(q.exists(q.ref_(q.class_("registers"), k)),
                            q.get_(q.ref_(q.class_("registers"), k)))
                     for k in ks])
                ts, instances = res[0], res[1:]
                registers = {}
                for k, inst in zip(ks, instances):
                    if isinstance(inst, dict):
                        registers[k] = {
                            "ts": inst.get("ts"),
                            "value": (inst.get("data") or {}).get("value")}
                return {**op, "type": "ok",
                        "value": {"ts": ts, "registers": registers}}
        elif self.mode == "internal":
            return self._internal_dispatch(q, op, f, v)
        elif self.mode == "g2":
            if f == "insert":
                k, ids = (v.key, v.value) if independent.is_tuple(v) \
                    else (v[0], v[1])
                a_id, b_id = ids
                tbl = "a" if a_id is not None else "b"
                the_id = a_id if a_id is not None else b_id
                empty = lambda n: q.equals(  # noqa: E731
                    q.select(["data"],
                             q.paginate(q.match(q.index(f"{n}-by-key"),
                                                k), size=1)), [])
                res = self.conn.query(q.if_(
                    q.and_(empty("a"), empty("b")),
                    q.do(q.create(q.ref_(q.class_(tbl), the_id),
                                  {"data": {"key": k, "id": the_id}}),
                         True),
                    False))
                return {**op, "type": "ok" if res else "fail"}
        return {**op, "type": "fail", "error": f"unknown f {f!r}"}


# ---------------------------------------------------------------------------
# pages: transactional isolation of pagination (pages.clj)
# ---------------------------------------------------------------------------

class PagesChecker(jchecker.Checker):
    """Every read must be expressible as a union of add-groups: pick an
    element, cross off its whole group, and if any group member is
    missing that's a pagination-isolation error (pages.clj:68-106)."""

    def check(self, test, history, opts):
        invoked, failed = set(), set()
        idx: dict = {}
        for o in history:
            if o.get("f") != "add":
                continue
            group = tuple(o.get("value") or ())
            if o.get("type") == "invoke":
                invoked.add(group)
            elif o.get("type") == "fail":
                failed.add(group)
        for group in invoked - failed:
            gs = frozenset(group)
            for e in group:
                assert e not in idx, "Elements must be unique"
                idx[e] = gs
        errs = []
        ok_reads = 0
        for o in history:
            if o.get("type") != "ok" or o.get("f") != "read":
                continue
            ok_reads += 1
            read = set(o.get("value") or ())
            while read:
                e = next(iter(read))
                group = idx.get(e, frozenset({e}))
                if not group <= read:
                    errs.append({"expected": sorted(group),
                                 "found": sorted(read & group)})
                read -= group
        return {"valid?": not errs,
                "ok-read-count": ok_reads,
                "error-count": len(errs),
                "first-error": errs[0] if errs else None}


def _pages_workload(opts: dict) -> dict:
    import random as _r
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    n = opts.get("pages-elements", 1000)
    group_size = opts.get("pages-group-size", 4)
    per_key = opts.get("pages-ops-per-key", 256)

    def gen_key(k):
        rng = _r.Random(f"pages:{k}")
        vals = list(range(-n, n))
        rng.shuffle(vals)
        groups = [vals[i:i + group_size]
                  for i in range(0, len(vals), group_size)]
        # ~4:1 adds:reads like the reference's (mix [adds x4 reads]) —
        # but interleaved into ONE sequence: our pure mix would step
        # four independent copies of the adds Seq, re-inserting every
        # group (the duplicate creates then fail and poison the
        # checker's group index).
        ops = []
        for g in groups:
            ops.append({"type": "invoke", "f": "add", "value": g})
            if rng.random() < 0.25:
                ops.append({"type": "invoke", "f": "read",
                            "value": None})
        return gen.stagger(1 / 5, gen.limit(per_key, gen.Seq.of(ops)))

    return {
        "client": FaunaClient(mode="pages",
                              naive_reads=bool(
                                  opts.get("pages-naive-reads"))),
        "generator": independent.concurrent_generator(
            2 * len(nodes), range(100000), gen_key),
        "checker": independent.checker(PagesChecker()),
    }


# ---------------------------------------------------------------------------
# multimonotonic: increment-only registers must never read backwards
# (multimonotonic.clj)
# ---------------------------------------------------------------------------

def _ts_sort_key(ts):
    """Sortable key for a read timestamp: Fauna @ts values arrive as
    microsecond ints or decoded ISO-8601 strings. Lexicographic string
    comparison mis-orders timestamps with differing fractional-second
    precision ('...T10:00:00Z' vs '...T10:00:00.5Z'), so ISO strings
    are parsed to epoch seconds; numerics are scaled to seconds too
    (micro/milli magnitudes detected by range, post-2001 epochs), so a
    history mixing raw and decoded forms still orders by actual time.
    Unparseable strings sort after everything, amongst themselves."""
    if isinstance(ts, str):
        try:
            from ..util import iso_to_epoch
            return (0, iso_to_epoch(ts))
        except ValueError:
            return (1, ts)
    v = float(ts)
    if v >= 1e14:        # microseconds since epoch (>= ~2001-09)
        v /= 1e6
    elif v >= 1e11:      # milliseconds since epoch
        v /= 1e3
    return (0, v)


class TsOrderChecker(jchecker.Checker):
    """Order reads by their read timestamp and fold a running lower
    bound per register; any read below the bound means timestamp order
    disagrees with observed values (multimonotonic.clj:256-272)."""

    def check(self, test, history, opts):
        reads = [o for o in history
                 if o.get("type") == "ok" and o.get("f") == "read"
                 and (o.get("value") or {}).get("ts") is not None]
        reads.sort(key=lambda o: _ts_sort_key(o["value"]["ts"]))
        inferred: dict = {}
        errs = []
        for o in reads:
            state = {k: r["value"]
                     for k, r in o["value"]["registers"].items()}
            bad = {k: [inferred[k], {"value": val,
                                     "op-index": o.get("index")}]
                   for k, val in state.items()
                   if k in inferred and val < inferred[k]["value"]}
            if bad:
                errs.append({"observed": state, "op": o, "errors": bad})
            for k, val in state.items():
                if k not in inferred or inferred[k]["value"] <= val:
                    inferred[k] = {"value": val,
                                   "op-index": o.get("index")}
        return {"valid?": not errs, "errors": errs[:8],
                "error-count": len(errs)}


class ReadSkewChecker(jchecker.Checker):
    """Read-skew hunt over increment-only registers: for each key,
    order reads by observed value and add edges between consecutive
    value classes; a cycle in the union graph means two reads each saw
    the other's future (the cycle-detection formulation sketched at
    multimonotonic.clj:274-299 — the reference stubs the check out;
    this implements it)."""

    def check(self, test, history, opts):
        reads = [o for o in history
                 if o.get("type") == "ok" and o.get("f") == "read"]
        states = [{k: r["value"]
                   for k, r in (o.get("value") or {}).get(
                       "registers", {}).items()} for o in reads]
        by_key: dict = {}
        for i, st in enumerate(states):
            for k, val in st.items():
                by_key.setdefault(k, {}).setdefault(val, []).append(i)
        edges: dict[int, set] = {i: set() for i in range(len(states))}
        for k, classes in by_key.items():
            vals = sorted(classes)
            for lo, hi in zip(vals, vals[1:]):
                for a in classes[lo]:
                    edges[a] |= set(classes[hi])
        # iterative Tarjan: any SCC with >1 node is a skew cycle
        index: dict = {}
        low: dict = {}
        on: set = set()
        stack: list = []
        sccs = []
        counter = [0]
        for root in edges:
            if root in index:
                continue
            work = [(root, iter(edges[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on.add(root)
            while work:
                v, it = work[-1]
                adv = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(edges[w])))
                        adv = True
                        break
                    if w in on:
                        low[v] = min(low[v], index[w])
                if adv:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[v])
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))
        errs = [{"cycle-reads": [reads[i].get("index") for i in comp],
                 "states": [states[i] for i in comp]} for comp in sccs]
        return {"valid?": not errs, "errors": errs[:8],
                "error-count": len(errs)}


class _MMWriter(gen.Generator):
    """Per-thread writer (via each_thread): blind writes to a key
    derived from the current process, restarting from 0 when a crash
    remaps the process (multimonotonic.clj:314-341). Registers written
    keys in the shared `active` dict so readers can pick subsets."""

    def __init__(self, active: dict, key=None, value: int = 0):
        self.active = active
        self.key = key
        self.value = value

    def op(self, test, ctx):
        thread = next(iter(ctx.workers))
        p = ctx.workers[thread]
        k, v = (self.key, self.value) if p == self.key else (p, 0)
        self.active[thread] = k
        o = gen.fill_in_op({"f": "write", "value": {k: v}}, ctx)
        if o is gen.PENDING:
            return (o, self)
        return (o, _MMWriter(self.active, k, v + 1))

    def update(self, test, ctx, event):
        return self


def _mm_workload(opts: dict) -> dict:
    import random as _r
    conc = int(str(opts.get("concurrency", 5)).rstrip("n")) or 5
    active: dict = {}

    def read(test=None, ctx=None):
        ks = sorted(set(active.values())) or [0]
        n = _r.randint(1, len(ks))
        return {"type": "invoke", "f": "read",
                "value": sorted(_r.sample(ks, n))}

    # Per-branch staggers: a single stagger around the reserve would
    # rate-limit the merged stream, and soonest-op tie-breaking then
    # starves the reader branch (a free writer thread wins every tick).
    return {
        "client": FaunaClient(mode="multimonotonic"),
        "generator": gen.reserve(
            max(1, conc // 2),
            gen.stagger(opts.get("mm-write-stagger", 1 / 200),
                        gen.each_thread(_MMWriter(active))),
            gen.stagger(opts.get("mm-read-stagger", 1 / 100), read)),
        "checker": jchecker.compose({
            "ts-order": TsOrderChecker(),
            "read-skew": ReadSkewChecker(),
        }),
    }


# ---------------------------------------------------------------------------
# internal transaction consistency (internal.clj)
# ---------------------------------------------------------------------------

class InternalChecker(jchecker.Checker):
    """Each create txn must NOT see its new cat in the pre-create read
    and MUST see it in the post-create read (both inside the same txn);
    change-type moves a cat between both index reads atomically
    (internal.clj:140-206)."""

    @staticmethod
    def _op_errors(op):
        v = op.get("value") or {}
        f = op.get("f")
        errs = []
        if f in ("create-tabby-let", "create-tabby-obj",
                 "create-tabby-arr"):
            name = ((v.get("tabby") or {}).get("data") or {}).get("name")
            if name in (v.get("tabbies-0") or []):
                errs.append({"type": "present-before-create",
                             "name": name, "op-index": op.get("index")})
            if name not in (v.get("tabbies-1") or []):
                errs.append({"type": "missing-after-create",
                             "name": name, "op-index": op.get("index")})
        elif f == "change-type":
            cat = v.get("cat")
            name = ((cat or {}).get("data") or {}).get("name")
            if name is not None:
                if name in (v.get("tabbies") or []):
                    errs.append({"type": "present-after-change",
                                 "name": name,
                                 "op-index": op.get("index")})
                if name not in (v.get("calicos") or []):
                    errs.append({"type": "missing-after-change",
                                 "name": name,
                                 "op-index": op.get("index")})
        return errs

    def check(self, test, history, opts):
        errors = [e for o in history if o.get("type") == "ok"
                  for e in self._op_errors(o)]
        return {"valid?": not errors,
                "error-count": len(errors),
                "error-types": sorted({e["type"] for e in errors}),
                "errors": errors[:16]}


def _internal_workload(opts: dict) -> dict:
    counter = {"i": -1}

    def create(f):
        def g(test=None, ctx=None):
            counter["i"] += 1
            return {"type": "invoke", "f": f, "value": counter["i"]}
        return g

    return {
        "client": FaunaClient(mode="internal"),
        "generator": gen.stagger(0.1, gen.mix(
            [create("create-tabby-let"), create("create-tabby-obj"),
             create("create-tabby-arr"),
             gen.repeat_gen({"type": "invoke", "f": "change-type",
                             "value": None})])),
        "checker": InternalChecker(),
    }


# ---------------------------------------------------------------------------
# replica-aware partitions (faunadb/nemesis.clj:20-55 + topology.clj:12-30)
# ---------------------------------------------------------------------------

def nodes_by_replica(nodes: list, replica_count: int = 3) -> dict:
    """The reference's initial layout: node i lives in replica
    i mod replica-count (topology.clj:12-30)."""
    out: dict = {}
    for i, n in enumerate(nodes):
        out.setdefault(f"replica-{i % replica_count}", []).append(n)
    return out


def intra_replica_grudge(replica_count: int = 3):
    """Partition INSIDE one randomly-chosen replica; nodes of other
    replicas keep uninterrupted connectivity to both halves
    (nemesis.clj:29-41)."""
    import random as _r

    def f(nodes):
        groups = sorted(nodes_by_replica(nodes, replica_count).items())
        _replica, members = _r.choice(groups)
        members = _r.sample(members, len(members))
        return jnemesis.complete_grudge(jnemesis.bisect(members))
    return f


def inter_replica_grudge(replica_count: int = 3):
    """Partition BETWEEN replicas: split the set of replicas in half
    and cut every cross-half link (nemesis.clj:42-55)."""
    import random as _r

    def f(nodes):
        groups = list(nodes_by_replica(nodes, replica_count).values())
        _r.shuffle(groups)
        halves = jnemesis.bisect(groups)
        flat = [[n for g in h for n in g] for h in halves]
        return jnemesis.complete_grudge(flat)
    return f


def single_node_grudge(nodes):
    """Isolate one node from everyone (nemesis.clj:20-28)."""
    return jnemesis.complete_grudge(jnemesis.split_one(nodes))


FAUNA_NEMESES = {
    "partition": jnemesis.partition_random_halves,
    "single-node-partition":
        lambda: jnemesis.partitioner(single_node_grudge),
    "intra-replica-partition":
        lambda: jnemesis.partitioner(intra_replica_grudge()),
    "inter-replica-partition":
        lambda: jnemesis.partitioner(inter_replica_grudge()),
}


# ---------------------------------------------------------------------------
# topology-change nemesis (topology.clj + auto.clj:107-124,273-280)
# ---------------------------------------------------------------------------

class TopologyNemesis:
    """Grow and shrink the cluster under load: `add-node` re-joins a
    removed node to the current primary (`faunadb-admin join -r
    <replica>`), `remove-node` removes it by host id (`faunadb-admin
    remove $(faunadb-admin host-id ...)`). Best-effort like the
    reference — topology drift after crashes is tolerated."""

    def __init__(self):
        self.removed: list = []

    def setup(self, test):
        return self

    def invoke(self, test, op):
        nodes = test.get("nodes") or []
        f = op.get("f")
        try:
            if f == "remove-node":
                cand = [n for n in nodes if n not in self.removed]
                if len(cand) <= (len(nodes) // 2 + 1):
                    return {**op, "type": "info", "value": "too-few"}
                node = cand[-1]
                sess = control.session(test, cand[0]).su()
                sess.exec("sh", "-c",
                          f"faunadb-admin remove "
                          f"$(faunadb-admin host-id {node})")
                self.removed.append(node)
                return {**op, "type": "info", "value": node}
            if f == "add-node":
                if not self.removed:
                    return {**op, "type": "info", "value": "none-removed"}
                node = self.removed.pop()
                primary = nodes[0]
                sess = control.session(test, node).su()
                sess.exec("faunadb-admin", "join", "-r", "replica-0",
                          primary)
                return {**op, "type": "info", "value": node}
            return {**op, "type": "info", "value": f"bad f {f!r}"}
        except Exception as e:  # noqa: BLE001 — nemesis never crashes a run
            return {**op, "type": "info", "error": str(e)[:120]}

    def teardown(self, test):
        pass


def topology_generator(interval: float = 15.0):
    return gen.stagger(interval, gen.cycle(gen.Seq.of([
        {"type": "info", "f": "remove-node"},
        {"type": "info", "f": "add-node"}])))


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    out = {}
    for k in ("register", "set", "bank", "monotonic", "g2"):
        def make(name=k):
            pkg = dict(std[name]())
            pkg.setdefault("client", FaunaClient(mode=name))
            return pkg
        out[k] = make
    o = opts or {}
    out["pages"] = lambda: _pages_workload(o)
    out["multimonotonic"] = lambda: _mm_workload(o)
    out["internal"] = lambda: _internal_workload(o)
    return out


def faunadb_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    choice = opts.get("nemesis", "partition")
    if choice == "topology":
        nem = TopologyNemesis()
    else:
        nem = FAUNA_NEMESES.get(choice, FAUNA_NEMESES["partition"])()
    return suite_test(
        "faunadb", wname, opts, workloads(opts),
        db=FaunaDB(opts.get("version", "2.5.5")),
        client=opts.get("client"),
        nemesis=nem,
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: faunadb_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="faunadb",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
