"""YCQL client: yugabyte's Cassandra-compatible API over drivers.cql.

Counterpart of the reference's YCQL client namespaces
(yugabyte/src/yugabyte/ycql/*, dual-API matrix at
yugabyte/src/yugabyte/core.clj:74-110). CQL semantics differ from SQL in
ways the workloads exploit:

  * INSERT is an upsert (no duplicate-key errors) -> set-adds dedupe,
  * CAS is a lightweight transaction: `UPDATE .. IF val = old`, whose
    result row is `[applied]` (+ current values when not applied),
  * multi-row atomicity is `BEGIN TRANSACTION .. END TRANSACTION;`
    blocks (writes only — reads can't join, so the bank transfer reads
    first, then writes computed balances in a txn block, exactly the
    reference's lost-update-prone shape the checker exists to catch),
  * lists are native: `val = val + [x]` appends.
"""

from __future__ import annotations

from .. import client as jclient
from .. import independent
from ..drivers import DBError, DriverError
from .sql import resolve

KEYSPACE = "jepsen"

DDL = [
    f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}",
    f"USE {KEYSPACE}",
    "CREATE TABLE IF NOT EXISTS registers (id bigint PRIMARY KEY,"
    " val bigint) WITH transactions = {'enabled': true}",
    "CREATE TABLE IF NOT EXISTS lists (id bigint PRIMARY KEY,"
    " val list<bigint>) WITH transactions = {'enabled': true}",
    "CREATE TABLE IF NOT EXISTS accounts (id bigint PRIMARY KEY,"
    " balance bigint) WITH transactions = {'enabled': true}",
    "CREATE TABLE IF NOT EXISTS sets (val bigint PRIMARY KEY)",
    "CREATE TABLE IF NOT EXISTS counter (id bigint PRIMARY KEY,"
    " val bigint) WITH transactions = {'enabled': true}",
]


class YCQLClient(jclient.Client):
    def __init__(self, mode: str = "register", port: int = 9042,
                 accounts: list | None = None, total: int = 100,
                 node: str | None = None, timeout: float = 10.0):
        self.mode = mode
        self.port = port
        self.accounts = accounts if accounts is not None else list(range(8))
        self.total = total
        self.node = node
        self.timeout = timeout
        self.conn = None
        self._setup_done = False

    def open(self, test, node):
        return YCQLClient(self.mode, self.port, self.accounts,
                          self.total, node, self.timeout)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import cql
            host, port = resolve(self.node, self.port, test or {})
            self.conn = cql.connect(host, port, timeout=self.timeout)
        if not self._setup_done:
            for stmt in DDL:
                self.conn.query(stmt)
            if self.mode == "bank":
                # INSERT IF NOT EXISTS: atomic seed (LWT)
                self.conn.query(
                    f"INSERT INTO accounts (id, balance) VALUES "
                    f"(0, {self.total}) IF NOT EXISTS")
                for a in self.accounts:
                    if a != 0:
                        self.conn.query(
                            f"INSERT INTO accounts (id, balance) VALUES "
                            f"({int(a)}, 0) IF NOT EXISTS")
            self._setup_done = True

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    #: CQL error codes whose outcome is UNKNOWN for a write: the
    #: coordinator lost track, but replicas may still apply it.
    #: 0x1100 WriteTimeout, 0x1400 WriteFailure, 0x0000 ServerError.
    AMBIGUOUS = {"cql-0x1100", "cql-0x1400", "cql-0x0000"}

    def invoke(self, test, op):
        read_only = op.get("f") == "read"
        try:
            self._ensure_conn(test)
            return self._dispatch(op)
        except DBError as e:
            ambiguous = str(e.code) in self.AMBIGUOUS and not read_only
            return {**op, "type": "info" if ambiguous else "fail",
                    "error": f"ycql-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    def _dispatch(self, op):
        if self.mode == "bank":
            return self._bank(op)
        if self.mode == "set":
            return self._set(op)
        if self.mode == "monotonic":
            return self._monotonic(op)
        if self.mode == "long-fork":
            return self._long_fork(op)
        return self._register(op)

    def _register(self, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        c = self.conn
        if op["f"] == "read":
            rows = c.query(f"SELECT val FROM registers "
                           f"WHERE id = {int(k)}").rows
            out = rows[0][0] if rows else None
            return {**op, "type": "ok", "value": lift(out)}
        if op["f"] == "write":
            c.query(f"INSERT INTO registers (id, val) VALUES "
                    f"({int(k)}, {int(val)})")
            return {**op, "type": "ok"}
        if op["f"] == "cas":
            old, new = val
            res = c.query(f"UPDATE registers SET val = {int(new)} "
                          f"WHERE id = {int(k)} IF val = {int(old)}")
            applied = bool(res.rows and res.rows[0][0])
            if applied:
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": "precondition"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _long_fork(self, op):
        """Long-fork over key registers: the whole-group read is ONE
        `IN`-clause SELECT (a single-statement snapshot read on a
        transactional table — the reference's approach,
        yugabyte/src/yugabyte/ycql/long_fork.clj:33-44); writes are
        single-row inserts. Read-write-mixed txns stay unsupported
        (reads can't join YCQL txn blocks), which is why append/wr are
        out of the YCQL matrix."""
        mops = op["value"]
        k0 = None
        if independent.is_tuple(mops):
            k0, mops = mops.key, mops.value
        c = self.conn
        if all(m[0] == "r" for m in mops):
            ks = sorted({int(m[1]) for m in mops})
            rows = c.query(
                f"SELECT id, val FROM registers WHERE id IN "
                f"({', '.join(str(k) for k in ks)})").rows
            got = {int(r[0]): (int(r[1]) if r[1] is not None else None)
                   for r in rows}
            out = [["r", mk, got.get(int(mk))] for _mf, mk, _mv in mops]
        elif len(mops) == 1 and mops[0][0] == "w":
            _, k, v = mops[0]
            c.query(f"INSERT INTO registers (id, val) VALUES "
                    f"({int(k)}, {int(v)})")
            out = [["w", k, v]]
        else:
            return {**op, "type": "fail",
                    "error": "ycql long-fork: mixed txn unsupported"}
        new_v = independent.tuple_(k0, out) if k0 is not None else out
        return {**op, "type": "ok", "value": new_v}

    def _bank(self, op):
        c = self.conn
        if op["f"] == "read":
            rows = c.query("SELECT id, balance FROM accounts").rows
            return {**op, "type": "ok",
                    "value": {int(r[0]): int(r[1]) for r in rows}}
        if op["f"] == "transfer":
            t = op["value"]
            frm, to, amt = int(t["from"]), int(t["to"]), int(t["amount"])
            # Server-side arithmetic inside the txn block — the
            # reference's shape (ycql/bank.clj:46-58). No balance
            # check, so overdrafts happen; the suite runs this workload
            # with negative balances allowed.
            c.query("BEGIN TRANSACTION "
                    f"UPDATE accounts SET balance = balance - {amt} "
                    f"WHERE id = {frm}; "
                    f"UPDATE accounts SET balance = balance + {amt} "
                    f"WHERE id = {to}; "
                    "END TRANSACTION;")
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _set(self, op):
        c = self.conn
        if op["f"] == "add":
            c.query(f"INSERT INTO sets (val) VALUES ({int(op['value'])})")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            rows = c.query("SELECT val FROM sets").rows
            return {**op, "type": "ok",
                    "value": sorted(int(r[0]) for r in rows)}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _monotonic(self, op):
        c = self.conn
        if op["f"] == "read":
            rows = c.query("SELECT val FROM counter WHERE id = 0").rows
            v = int(rows[0][0]) if rows and rows[0][0] is not None else None
            return {**op, "type": "ok", "value": v}
        if op["f"] == "inc":
            # LWT loop: CAS val -> val+1 (the reference's counter
            # workload shape)
            for _ in range(16):
                rows = c.query("SELECT val FROM counter "
                               "WHERE id = 0").rows
                cur = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else None
                if cur is None:
                    res = c.query("INSERT INTO counter (id, val) VALUES "
                                  "(0, 1) IF NOT EXISTS")
                else:
                    res = c.query(f"UPDATE counter SET val = {cur + 1} "
                                  f"WHERE id = 0 IF val = {cur}")
                if bool(res.rows and res.rows[0][0]):
                    return {**op, "type": "ok",
                            "value": 1 if cur is None else cur + 1}
            return {**op, "type": "fail", "error": "cas-exhausted"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}


#: workload -> YCQL mode (the reference's YCQL matrix: bank, counter,
#: long-fork, set, single/multi-key-acid — no append/wr, whose
#: read-write txns can't be expressed in YCQL txn blocks)
MODES = {"register": "register", "set": "set", "bank": "bank",
         "monotonic": "monotonic", "long-fork": "long-fork"}


def client_for(workload: str, opts: dict | None = None) -> YCQLClient:
    opts = opts or {}
    if workload not in MODES:
        raise ValueError(
            f"workload {workload!r} has no YCQL client (reads can't "
            f"join YCQL txn blocks); supported: {sorted(MODES)}")
    return YCQLClient(MODES[workload],
                      accounts=opts.get("accounts"),
                      total=opts.get("total-amount", 100))
