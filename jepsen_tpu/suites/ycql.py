"""YCQL client: yugabyte's Cassandra-compatible API over drivers.cql.

Counterpart of the reference's YCQL client namespaces
(yugabyte/src/yugabyte/ycql/*, dual-API matrix at
yugabyte/src/yugabyte/core.clj:74-110). CQL semantics differ from SQL in
ways the workloads exploit:

  * INSERT is an upsert (no duplicate-key errors) -> set-adds dedupe,
  * CAS is a lightweight transaction: `UPDATE .. IF val = old`, whose
    result row is `[applied]` (+ current values when not applied),
  * multi-row atomicity is `BEGIN TRANSACTION .. END TRANSACTION;`
    blocks (writes only — reads can't join, so the bank transfer reads
    first, then writes computed balances in a txn block, exactly the
    reference's lost-update-prone shape the checker exists to catch),
  * lists are native: `val = val + [x]` appends.
"""

from __future__ import annotations

from .. import client as jclient
from .. import independent
from ..drivers import DBError, DriverError
from .sql import resolve

KEYSPACE = "jepsen"

DDL = [
    f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}",
    f"USE {KEYSPACE}",
    "CREATE TABLE IF NOT EXISTS registers (id bigint PRIMARY KEY,"
    " val bigint) WITH transactions = {'enabled': true}",
    "CREATE TABLE IF NOT EXISTS lists (id bigint PRIMARY KEY,"
    " val list<bigint>) WITH transactions = {'enabled': true}",
    "CREATE TABLE IF NOT EXISTS accounts (id bigint PRIMARY KEY,"
    " balance bigint) WITH transactions = {'enabled': true}",
    "CREATE TABLE IF NOT EXISTS sets (val bigint PRIMARY KEY)",
    "CREATE TABLE IF NOT EXISTS counter (id bigint PRIMARY KEY,"
    " val bigint) WITH transactions = {'enabled': true}",
]


class YCQLClient(jclient.Client):
    def __init__(self, mode: str = "register", port: int = 9042,
                 accounts: list | None = None, total: int = 100,
                 node: str | None = None, timeout: float = 10.0):
        self.mode = mode
        self.port = port
        self.accounts = accounts if accounts is not None else list(range(8))
        self.total = total
        self.node = node
        self.timeout = timeout
        self.conn = None
        self._setup_done = False

    def open(self, test, node):
        return YCQLClient(self.mode, self.port, self.accounts,
                          self.total, node, self.timeout)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import cql
            host, port = resolve(self.node, self.port, test or {})
            self.conn = cql.connect(host, port, timeout=self.timeout)
        if not self._setup_done:
            for stmt in DDL:
                self.conn.query(stmt)
            if self.mode == "bank":
                # INSERT IF NOT EXISTS: atomic seed (LWT)
                self.conn.query(
                    f"INSERT INTO accounts (id, balance) VALUES "
                    f"(0, {self.total}) IF NOT EXISTS")
                for a in self.accounts:
                    if a != 0:
                        self.conn.query(
                            f"INSERT INTO accounts (id, balance) VALUES "
                            f"({int(a)}, 0) IF NOT EXISTS")
            self._setup_done = True

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def invoke(self, test, op):
        read_only = op.get("f") == "read"
        try:
            self._ensure_conn(test)
            return self._dispatch(op)
        except DBError as e:
            return {**op, "type": "fail",
                    "error": f"ycql-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    def _dispatch(self, op):
        if self.mode == "bank":
            return self._bank(op)
        if self.mode == "set":
            return self._set(op)
        if self.mode == "monotonic":
            return self._monotonic(op)
        if self.mode == "append":
            return self._append(op)
        return self._register(op)

    def _register(self, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        c = self.conn
        if op["f"] == "read":
            rows = c.query(f"SELECT val FROM registers "
                           f"WHERE id = {int(k)}").rows
            out = rows[0][0] if rows else None
            return {**op, "type": "ok", "value": lift(out)}
        if op["f"] == "write":
            c.query(f"INSERT INTO registers (id, val) VALUES "
                    f"({int(k)}, {int(val)})")
            return {**op, "type": "ok"}
        if op["f"] == "cas":
            old, new = val
            res = c.query(f"UPDATE registers SET val = {int(new)} "
                          f"WHERE id = {int(k)} IF val = {int(old)}")
            applied = bool(res.rows and res.rows[0][0])
            if applied:
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": "precondition"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _append(self, op):
        mops = op["value"]
        k0 = None
        if independent.is_tuple(mops):
            k0, mops = mops.key, mops.value
        c = self.conn
        out = []
        # single-mop txns run direct; multi-mop writes use a txn block.
        writes = [m for m in mops if m[0] == "append"]
        if len(writes) > 1:
            block = "BEGIN TRANSACTION " + " ".join(
                f"UPDATE lists SET val = val + [{int(v)}] "
                f"WHERE id = {int(k)};" for _, k, v in writes) + \
                " END TRANSACTION;"
            c.query(block)
        for mf, mk, mv in mops:
            if mf == "append":
                if len(writes) <= 1:
                    c.query(f"UPDATE lists SET val = val + [{int(mv)}] "
                            f"WHERE id = {int(mk)}")
                out.append([mf, mk, mv])
            else:
                rows = c.query(f"SELECT val FROM lists "
                               f"WHERE id = {int(mk)}").rows
                vals = rows[0][0] if rows and rows[0][0] else []
                out.append([mf, mk, list(vals)])
        new_v = independent.tuple_(k0, out) if k0 is not None else out
        return {**op, "type": "ok", "value": new_v}

    def _bank(self, op):
        c = self.conn
        if op["f"] == "read":
            rows = c.query("SELECT id, balance FROM accounts").rows
            return {**op, "type": "ok",
                    "value": {int(r[0]): int(r[1]) for r in rows}}
        if op["f"] == "transfer":
            t = op["value"]
            frm, to, amt = int(t["from"]), int(t["to"]), int(t["amount"])
            rows = c.query(f"SELECT balance FROM accounts "
                           f"WHERE id = {frm}").rows
            b1 = int(rows[0][0]) if rows else 0
            if b1 < amt:
                return {**op, "type": "fail", "error": "insufficient"}
            rows = c.query(f"SELECT balance FROM accounts "
                           f"WHERE id = {to}").rows
            b2 = int(rows[0][0]) if rows else 0
            c.query("BEGIN TRANSACTION "
                    f"UPDATE accounts SET balance = {b1 - amt} "
                    f"WHERE id = {frm}; "
                    f"UPDATE accounts SET balance = {b2 + amt} "
                    f"WHERE id = {to}; "
                    "END TRANSACTION;")
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _set(self, op):
        c = self.conn
        if op["f"] == "add":
            c.query(f"INSERT INTO sets (val) VALUES ({int(op['value'])})")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            rows = c.query("SELECT val FROM sets").rows
            return {**op, "type": "ok",
                    "value": sorted(int(r[0]) for r in rows)}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _monotonic(self, op):
        c = self.conn
        if op["f"] == "read":
            rows = c.query("SELECT val FROM counter WHERE id = 0").rows
            v = int(rows[0][0]) if rows and rows[0][0] is not None else None
            return {**op, "type": "ok", "value": v}
        if op["f"] == "inc":
            # LWT loop: CAS val -> val+1 (the reference's counter
            # workload shape)
            for _ in range(16):
                rows = c.query("SELECT val FROM counter "
                               "WHERE id = 0").rows
                cur = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else None
                if cur is None:
                    res = c.query("INSERT INTO counter (id, val) VALUES "
                                  "(0, 1) IF NOT EXISTS")
                else:
                    res = c.query(f"UPDATE counter SET val = {cur + 1} "
                                  f"WHERE id = 0 IF val = {cur}")
                if bool(res.rows and res.rows[0][0]):
                    return {**op, "type": "ok",
                            "value": 1 if cur is None else cur + 1}
            return {**op, "type": "fail", "error": "cas-exhausted"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}


#: workload -> YCQL mode (the reference's YCQL matrix subset: no wr /
#: long-fork — reads can't join YCQL txn blocks)
MODES = {"register": "register", "set": "set", "bank": "bank",
         "monotonic": "monotonic", "append": "append"}


def client_for(workload: str, opts: dict | None = None) -> YCQLClient:
    opts = opts or {}
    return YCQLClient(MODES.get(workload, "register"),
                      accounts=opts.get("accounts"),
                      total=opts.get("total-amount", 100))
