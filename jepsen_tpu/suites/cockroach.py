"""CockroachDB suite — config #3 of the north star.

Counterpart of cockroachdb/src/jepsen/cockroach.clj and its workloads
(register, bank, monotonic, sequential, sets, comments/g2; SURVEY.md
§2.6): a single-binary tarball install with a multi-node --join cluster,
and a workload matrix built from the shared library. SQL access is
driver-pluggable: pass ``connect_fn`` (a psycopg2-compatible connect)
into the client; the workload/checker layer is complete without it (the
analyze path for stored histories needs no driver at all).
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..nemesis import clock as jclock
from ..control import util as cutil
from . import base_opts, sql, standard_workloads, suite_test

VERSION = "v19.1.5"
DIR = "/opt/cockroach"
BINARY = f"{DIR}/cockroach"
LOGFILE = f"{DIR}/cockroach.log"
PIDFILE = f"{DIR}/cockroach.pid"


class CockroachDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """Tarball install + `cockroach start --join` cluster
    (cockroachdb/src/jepsen/cockroach.clj's db); kill/pause fault
    protocols via SignalProcess."""

    process_pattern = "cockroach"

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start(self, sess, test, node):
        join = ",".join(f"{n}:26257" for n in test.get("nodes", []))
        cutil.start_daemon(
            sess, BINARY, "start", "--insecure",
            "--store", f"{DIR}/data",
            "--listen-addr", f"{node}:26257",
            "--http-addr", f"{node}:8080",
            "--join", join,
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://binaries.cockroachdb.com/"
               f"cockroach-{self.version}.linux-amd64.tgz")
        cutil.install_archive(sess, url, DIR)
        self._start(sess, test, node)
        if node == (test.get("nodes") or [node])[0]:
            # The daemon launch returns before the server listens; retry
            # init until it connects. "already been initialized" (from a
            # prior cycle) also counts as success.
            import time
            last = None
            for _ in range(30):
                res = sess.exec_ok(BINARY, "init", "--insecure",
                                   "--host", f"{node}:26257")
                if res.exit == 0 or "already been initialized" in res.err:
                    break
                last = res
                time.sleep(1)
            else:
                raise control.CommandError(
                    "cockroach init", last.exit if last else -1,
                    last.out if last else "", last.err if last else "",
                    node)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    from ..workloads import comments as comments_wl
    std = standard_workloads(opts)
    # cockroach's matrix: register, bank, monotonic, sequential, sets,
    # g2 from the shared library, plus the suite's signature comments
    # strict-serializability check (cockroach/comments.clj:1-160).
    out = {k: std[k] for k in
           ("register", "bank", "monotonic", "sequential", "set", "g2")}
    out["comments"] = lambda: comments_wl.workload(opts)
    return out


def default_client(workload: str, opts: dict):
    """pg-wire client on cockroach's SQL port (the reference drives
    cockroach through jdbc/postgres, cockroach/client.clj:1-60)."""
    return sql.client_for(
        sql.PGDialect(port=26257, user="root", database="defaultdb"),
        workload, opts)


#: The reference cockroach suite's nemesis menu
#: (cockroachdb/src/jepsen/cockroach/nemesis.clj): partitions, clock
#: skew via the on-node bump/strobe helpers, and process pauses.
NEMESES = {
    "none": jnemesis.noop,
    "partition": jnemesis.partition_random_halves,
    "partition-half": jnemesis.partition_halves,
    "partition-ring": jnemesis.partition_majorities_ring,
    "clock": jclock.clock_nemesis,
    "pause": lambda: jnemesis.hammer_time("cockroach"),
}


def cockroach_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    nemesis_name = opts.get("nemesis", "partition")
    if nemesis_name not in NEMESES:
        raise ValueError(f"unknown nemesis {nemesis_name!r}; "
                         f"have {sorted(NEMESES)}")
    test = suite_test(
        "cockroach", wname, opts,
        workloads(opts),
        db=CockroachDB(opts.get("version", VERSION)),
        client=opts.get("client") or default_client(wname, opts),
        nemesis=NEMESES[nemesis_name](),
        os_setup=os_setup.debian())
    test["nemesis-name"] = nemesis_name
    return test


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: cockroach_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register"),
             "nemesis": getattr(args, "nemesis", "partition")}),
        name="cockroach",
        opt_fn=lambda p: (
            p.add_argument("--workload", default=None,
                           choices=sorted(workloads())),
            p.add_argument("--nemesis", default="partition",
                           choices=sorted(NEMESES))),
        tests_fn=lambda tmap, args: [
            cockroach_test({**tmap, "workload": w,
                            "nemesis": getattr(args, "nemesis",
                                               "partition")})
            for w in ([args.workload] if getattr(
                args, "workload", None) else sorted(workloads()))],
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
