"""Apache Ignite suite.

Counterpart of ignite/src/jepsen/ignite/ (549 LoC + the thick-client
Client.java/Bank.java workload): a zip-installed Ignite node per host
with static IP discovery, bank and register workloads. The client
protocol is Ignite's JVM binary protocol — pluggable (pass
``client``); install/daemon/workload wiring is complete.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, standard_workloads, suite_test

DIR = "/opt/ignite"
VERSION = "2.7.0"
PIDFILE = f"{DIR}/ignite.pid"
LOGFILE = f"{DIR}/ignite.log"


class IgniteDB(jdb.DB, jdb.LogFiles):
    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "openjdk-8-jre-headless")
        url = (f"https://archive.apache.org/dist/ignite/{self.version}/"
               f"apache-ignite-{self.version}-bin.zip")
        cutil.install_archive(sess, url, DIR)
        nodes = test.get("nodes", [node])
        addrs = "\n".join(
            f"            <value>{n}:47500</value>" for n in nodes)
        cfg = ("<beans xmlns=\"http://www.springframework.org/schema/"
               "beans\">\n <bean class=\"org.apache.ignite."
               "configuration.IgniteConfiguration\">\n  <property "
               "name=\"discoverySpi\">\n   <bean class=\"org.apache."
               "ignite.spi.discovery.tcp.TcpDiscoverySpi\">\n"
               "    <property name=\"ipFinder\">\n     <bean class="
               "\"org.apache.ignite.spi.discovery.tcp.ipfinder.vm."
               "TcpDiscoveryVmIpFinder\">\n      <property name="
               "\"addresses\">\n       <list>\n"
               f"{addrs}\n       </list>\n      </property>\n     "
               "</bean>\n    </property>\n   </bean>\n  </property>\n"
               " </bean>\n</beans>\n")
        sess.exec("sh", "-c",
                  f"cat > {DIR}/config/jepsen.xml << 'EOF'\n{cfg}\nEOF")
        cutil.start_daemon(
            sess, f"{DIR}/bin/ignite.sh", f"{DIR}/config/jepsen.xml",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/work")

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {k: std[k] for k in ("bank", "register", "set")}


def ignite_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "bank")
    return suite_test(
        "ignite", wname, opts, workloads(opts),
        db=IgniteDB(opts.get("version", VERSION)),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: ignite_test(
            {**tmap, "workload": resolve_workload(args, tmap, "bank")}),
        name="ignite",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
