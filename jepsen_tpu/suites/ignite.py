"""Apache Ignite suite.

Counterpart of ignite/src/jepsen/ignite/ (549 LoC + the thick-client
Client.java/Bank.java workload): a zip-installed Ignite node per host
with static IP discovery, driven over the thin-client binary protocol
(drivers/ignite_thin.py) — register CAS on a transactional cache and
the bank transfer workload inside PESSIMISTIC/REPEATABLE_READ
transactions, matching Client.java/Bank.java's semantics.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import independent
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from ..drivers import DriverError
from ..drivers import ignite_thin as ig
from ..workloads import bank as bank_wl
from . import base_opts, standard_workloads, suite_test

DIR = "/opt/ignite"
VERSION = "2.7.0"
PIDFILE = f"{DIR}/ignite.pid"
LOGFILE = f"{DIR}/ignite.log"


class IgniteDB(jdb.DB, jdb.LogFiles):
    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "openjdk-8-jre-headless")
        url = (f"https://archive.apache.org/dist/ignite/{self.version}/"
               f"apache-ignite-{self.version}-bin.zip")
        cutil.install_archive(sess, url, DIR)
        nodes = test.get("nodes", [node])
        addrs = "\n".join(
            f"            <value>{n}:47500</value>" for n in nodes)
        cfg = ("<beans xmlns=\"http://www.springframework.org/schema/"
               "beans\">\n <bean class=\"org.apache.ignite."
               "configuration.IgniteConfiguration\">\n  <property "
               "name=\"discoverySpi\">\n   <bean class=\"org.apache."
               "ignite.spi.discovery.tcp.TcpDiscoverySpi\">\n"
               "    <property name=\"ipFinder\">\n     <bean class="
               "\"org.apache.ignite.spi.discovery.tcp.ipfinder.vm."
               "TcpDiscoveryVmIpFinder\">\n      <property name="
               "\"addresses\">\n       <list>\n"
               f"{addrs}\n       </list>\n      </property>\n     "
               "</bean>\n    </property>\n   </bean>\n  </property>\n"
               " </bean>\n</beans>\n")
        sess.exec("sh", "-c",
                  f"cat > {DIR}/config/jepsen.xml << 'EOF'\n{cfg}\nEOF")
        cutil.start_daemon(
            sess, f"{DIR}/bin/ignite.sh", f"{DIR}/config/jepsen.xml",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/work")

    def log_files(self, test, node):
        return [LOGFILE]


CACHE = "jepsen"


class _IgClient(jclient.Client):
    port = 10800

    def __init__(self, conn: ig.IgniteConn | None = None,
                 port: int | None = None):
        self.conn = conn
        if port is not None:
            self.port = port

    def open(self, test, node):
        conn = ig.IgniteConn(node, self.port)
        try:
            conn.get_or_create_cache(CACHE)
        except ig.IgniteError:
            pass  # already exists / cluster not ready: ops will surface it
        return type(self)(conn, port=self.port)

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


class IgniteRegisterClient(_IgClient):
    """Per-key CAS register over cache ops (Client.java's cache surface:
    get / put / replace(k, old, new))."""

    def invoke(self, test, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = ((lambda x: independent.tuple_(k, x))
                if independent.is_tuple(v) else (lambda x: x))
        try:
            if op["f"] == "read":
                return {**op, "type": "ok",
                        "value": lift(self.conn.get(CACHE, f"r{k}"))}
            if op["f"] == "write":
                self.conn.put(CACHE, f"r{k}", val)
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = val
                if old is None:
                    ok = self.conn.put_if_absent(CACHE, f"r{k}", new)
                else:
                    ok = self.conn.replace_if_equals(
                        CACHE, f"r{k}", old, new)
                return {**op, "type": "ok" if ok else "fail",
                        **({} if ok else {"error": "cas-failed"})}
            return {**op, "type": "fail", "error": f"bad f {op['f']!r}"}
        except DriverError as e:
            crash = "fail" if op["f"] == "read" else "info"
            return {**op, "type": crash, "error": str(e)[:120]}


class IgniteBankClient(_IgClient):
    """Transfers inside thin-client transactions (Bank.java runs
    PESSIMISTIC / REPEATABLE_READ around read-modify-write pairs)."""

    accounts = tuple(bank_wl.DEFAULT_ACCOUNTS)
    total = bank_wl.DEFAULT_TOTAL

    def open(self, test, node):
        c = super().open(test, node)
        per = self.total // len(self.accounts)
        rem = self.total - per * len(self.accounts)
        try:
            for a in self.accounts:
                c.conn.put_if_absent(CACHE, f"acct{a}",
                                     per + (rem if a == 0 else 0))
        except DriverError:
            pass
        return c

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                tx = self.conn.tx_start()
                try:
                    out = {a: self.conn.get(CACHE, f"acct{a}", tx=tx)
                           for a in self.accounts}
                    self.conn.tx_end(tx, True)
                except BaseException:
                    self.conn.tx_end(tx, False)
                    raise
                return {**op, "type": "ok", "value": out}
            if op["f"] == "transfer":
                v = op["value"]
                frm, to, amt = v["from"], v["to"], v["amount"]
                tx = self.conn.tx_start()
                try:
                    b1 = self.conn.get(CACHE, f"acct{frm}", tx=tx)
                    b2 = self.conn.get(CACHE, f"acct{to}", tx=tx)
                    if b1 is None or b1 < amt:
                        self.conn.tx_end(tx, False)
                        return {**op, "type": "fail",
                                "error": "insufficient"}
                    self.conn.put(CACHE, f"acct{frm}", b1 - amt, tx=tx)
                    self.conn.put(CACHE, f"acct{to}", (b2 or 0) + amt,
                                  tx=tx)
                    self.conn.tx_end(tx, True)
                except BaseException:
                    try:
                        self.conn.tx_end(tx, False)
                    except DriverError:
                        pass
                    raise
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": f"bad f {op['f']!r}"}
        except ig.IgniteError as e:
            return {**op, "type": "fail", "error": str(e)[:120]}
        except DriverError as e:
            crash = "fail" if op["f"] == "read" else "info"
            return {**op, "type": crash, "error": str(e)[:120]}


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {
        "bank": lambda: {**std["bank"](), "client": IgniteBankClient()},
        "register": lambda: {**std["register"](),
                             "client": IgniteRegisterClient()},
        "set": std["set"],
    }


def ignite_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "bank")
    return suite_test(
        "ignite", wname, opts, workloads(opts),
        db=IgniteDB(opts.get("version", VERSION)),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: ignite_test(
            {**tmap, "workload": resolve_workload(args, tmap, "bank")}),
        name="ignite",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
