"""MongoDB-on-SmartOS suite.

Counterpart of mongodb-smartos/src/jepsen/mongodb/ (788 LoC): the
mongodb suite provisioned on SmartOS nodes (pkgin packaging, SMF
service management) instead of Debian.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import os_setup
from . import mongodb


def mongodb_smartos_test(opts: dict | None = None) -> dict:
    return mongodb.mongodb_test(opts, name="mongodb-smartos",
                                os_module=os_setup.smartos())


def workloads(opts: dict | None = None) -> dict:
    return mongodb.workloads(opts)


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: mongodb_smartos_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="mongodb-smartos",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
