"""Raftis suite.

Counterpart of raftis/src/jepsen/raftis.clj (142 LoC): a
redis-protocol store replicated over raft, driven with plain SET/GET
register ops (the reference has no CAS — raftis doesn't expose one,
raftis.clj:20-21,39-47) and checked for per-key linearizability. The
client is the in-tree RESP driver.
"""

from __future__ import annotations

import random

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent, nemesis as jnemesis, os_setup
from ..checker import models
from ..control import util as cutil
from ..drivers import DBError, DriverError
from . import base_opts, nemesis_cycle
from .sql import resolve

DIR = "/opt/raftis"
PORT = 6379
PIDFILE = f"{DIR}/raftis.pid"
LOGFILE = f"{DIR}/raftis.log"


class RaftisDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """go build + daemonize with the peer list (db, raftis.clj:79-110);
    kill/pause fault protocols via SignalProcess."""

    process_pattern = "raftis"

    def _start(self, sess, test, node):
        nodes = test.get("nodes", [node])
        cluster = ",".join(f"{n}:{PORT}" for n in nodes)
        cutil.start_daemon(
            sess, f"{DIR}/raftis",
            "-hosts", cluster,
            "-bind", f"{node}:{PORT}",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("sh", "-c",
                  f"test -d {DIR} || git clone "
                  f"https://github.com/goraft/raftis {DIR}")
        sess.exec("sh", "-c", f"cd {DIR} && go build -o raftis .")
        self._start(sess, test, node)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [LOGFILE]


class RaftisClient(jclient.Client):
    """SET/GET register over RESP (client, raftis.clj:28-52); NOLEADER
    errors are definite fails, timeouts indeterminate for writes."""

    def __init__(self, port: int = PORT, node: str | None = None,
                 timeout: float = 5.0):
        self.port = port
        self.node = node
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        return RaftisClient(self.port, node, self.timeout)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import resp
            host, port = resolve(self.node, self.port, test or {})
            self.conn = resp.connect(host, port, self.timeout)

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def invoke(self, test, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        read_only = op["f"] == "read"
        try:
            self._ensure_conn(test)
            if op["f"] == "read":
                out = self.conn.command("GET", f"r{k}")
                return {**op, "type": "ok",
                        "value": lift(int(out) if out else None)}
            if op["f"] == "write":
                self.conn.command("SET", f"r{k}", int(val))
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except DBError as e:
            # NOLEADER / MOVED style rejections are definite
            return {**op, "type": "fail",
                    "error": f"raftis-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}


def r(test=None, ctx=None):
    return {"type": "invoke", "f": "read", "value": None}


def w(test=None, ctx=None):
    return {"type": "invoke", "f": "write", "value": random.randint(0, 4)}


def workloads(opts: dict | None = None) -> dict:
    def register():
        return {
            "generator": independent.concurrent_generator(
                2, range(10_000),
                lambda k: gen.limit(100, gen.mix([r, w]))),
            "checker": independent.checker(
                jchecker.linearizable(models.register())),
        }

    return {"register": register}


def raftis_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wl = workloads(opts)["register"]()
    test = {
        "name": "raftis register",
        "os": os_setup.debian(),
        "db": RaftisDB(),
        "client": opts.get("client") or RaftisClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": wl["checker"],
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(wl["generator"],
                        nemesis_cycle(opts.get("nemesis-interval", 10)))),
        "workload": "register",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: raftis_test(tmap),
                        name="raftis", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
