"""LogCabin suite.

Counterpart of logcabin/src/jepsen/logcabin.clj (246 LoC): the raft
reference implementation, built from source, bootstrapped on node 0
and reconfigured to the full member set; register workload over its
tree store. LogCabin's client protocol is its own protobuf RPC — the
wire client is pluggable (pass ``client``); the reference itself
drives ops through the `logcabin` CLI binary, and so does the default
client here (exec over SSH).
"""

from __future__ import annotations

from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent, nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, nemesis_cycle

DIR = "/opt/logcabin"
PIDFILE = f"{DIR}/logcabin.pid"
LOGFILE = f"{DIR}/logcabin.log"


class LogCabinDB(jdb.DB, jdb.LogFiles):
    """git + scons build, bootstrap on node 0, daemonize
    (install!/db, logcabin.clj:23-140)."""

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "git-core", "scons",
                  "g++", "protobuf-compiler", "libprotobuf-dev",
                  "libcrypto++-dev")
        sess.exec("sh", "-c",
                  f"test -d {DIR} || git clone "
                  f"https://github.com/logcabin/logcabin {DIR}")
        sess.exec("sh", "-c",
                  f"cd {DIR} && git submodule update --init && scons")
        nodes = test.get("nodes", [node])
        sid = nodes.index(node) + 1 if node in nodes else 1
        cfg = "\n".join([f"serverId = {sid}",
                         f"listenAddresses = {node}:5254",
                         f"storagePath = {DIR}/storage"])
        sess.exec("sh", "-c",
                  f"cat > {DIR}/logcabin.conf << 'EOF'\n{cfg}\nEOF")
        if node == nodes[0]:
            sess.exec(f"{DIR}/build/LogCabin",
                      "--config", f"{DIR}/logcabin.conf", "--bootstrap")
        cutil.start_daemon(
            sess, f"{DIR}/build/LogCabin",
            "--config", f"{DIR}/logcabin.conf",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/storage")

    def log_files(self, test, node):
        return [LOGFILE]


#: TreeOps conditional-write failure: the register held a different
#: value than the CAS precondition demanded (cas-msg-pattern,
#: logcabin.clj:152-155) — a *definite* failure.
CAS_FAILED = "as required"
#: Client-side op timeout (timeout-msg-pattern, logcabin.clj:157-158).
#: The reference maps this to :fail with :value :timed-out.
TIMED_OUT = "timeout elapsed"
OP_TIMEOUT = 3  # seconds (op-timeout, logcabin.clj:160-162)


class LogCabinClient(jclient.Client):
    """Register ops via the on-node `TreeOps` binary over SSH — exactly
    how the reference drives LogCabin (logcabin-get!/set!/cas!,
    logcabin.clj:164-209): reads and writes through the tree store, CAS
    via TreeOps' `-p path:oldvalue` conditional write."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return LogCabinClient(node)

    def _treeops(self, cluster: str) -> str:
        return (f"{DIR}/build/Examples/TreeOps "
                f"--cluster={cluster} -q -t {OP_TIMEOUT}")

    def invoke(self, test, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        sess = control.session(test, self.node)
        cluster = ",".join(f"{n}:5254" for n in test.get("nodes", []))
        top = self._treeops(cluster)
        try:
            if op["f"] == "read":
                res = sess.exec_raw(f"{top} read /r{k}")
                if res.exit != 0:
                    raise control.CommandError(
                        "treeops read", res.exit, res.out, res.err,
                        self.node)
                out = res.out.strip()
                return {**op, "type": "ok",
                        "value": lift(int(out) if out else None)}
            if op["f"] == "write":
                sess.exec("sh", "-c",
                          f"echo -n {int(val)} | {top} write /r{k}")
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = val
                sess.exec("sh", "-c",
                          f"echo -n {int(new)} | "
                          f"{top} -p /r{k}:{int(old)} write /r{k}")
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except control.CommandError as e:
            msg = (e.err or e.out or "").strip()
            if op["f"] == "cas" and CAS_FAILED in msg:
                return {**op, "type": "fail", "error": "cas-mismatch"}
            if TIMED_OUT in msg:
                # The reference maps every client timeout to
                # :fail/:timed-out (logcabin.clj:240-243) — unsound for
                # writes, which may commit after the client gives up.
                # Reads are idempotent, so fail is safe there; timed-out
                # writes/cas are indeterminate.
                if op["f"] == "read":
                    return {**op, "type": "fail", "error": "timed-out"}
                return {**op, "type": "info", "error": "timed-out"}
            if op["f"] == "read":
                # A never-written register reads as absent; the
                # reference avoids this by seeding nil in setup!
                # (logcabin.clj:214-216) — treat TreeOps' lookup
                # failure as an ok nil read rather than fail-noise.
                # scoped to TreeOps' lookup errors — a broader match
                # (e.g. the shell's "TreeOps: not found") would turn
                # infrastructure failures into fabricated ok reads
                if any(s in msg.lower() for s in
                       ("lookup_error", "does not exist")):
                    return {**op, "type": "ok", "value": lift(None)}
                return {**op, "type": "fail", "error": str(e)[:120]}
            # a failed write/cas exec is indeterminate: TreeOps may
            # have committed before dying
            return {**op, "type": "info", "error": str(e)[:120]}
        except control.ConnectionError_ as e:
            crash = "fail" if op["f"] == "read" else "info"
            return {**op, "type": crash, "error": str(e)[:120]}
        finally:
            sess.disconnect()


def workloads(opts: dict | None = None) -> dict:
    from ..workloads import register as register_wl

    def register():
        # r/w/cas mix against the CAS-register model, per the
        # reference's CASClient (logcabin.clj:212-250)
        return {
            "generator": register_wl.generator(2, 10_000, 100),
            "checker": register_wl.checker(),
        }

    return {"register": register}


def logcabin_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wl = workloads(opts)["register"]()
    test = {
        "name": "logcabin register",
        "os": os_setup.debian(),
        "db": LogCabinDB(),
        "client": opts.get("client") or LogCabinClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": wl["checker"],
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(wl["generator"],
                        nemesis_cycle(opts.get("nemesis-interval", 10)))),
        "workload": "register",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: logcabin_test(tmap),
                        name="logcabin", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
