"""LogCabin suite.

Counterpart of logcabin/src/jepsen/logcabin.clj (246 LoC): the raft
reference implementation, built from source, bootstrapped on node 0
and reconfigured to the full member set; register workload over its
tree store. LogCabin's client protocol is its own protobuf RPC — the
wire client is pluggable (pass ``client``); the reference itself
drives ops through the `logcabin` CLI binary, and so does the default
client here (exec over SSH).
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent, nemesis as jnemesis, os_setup
from ..checker import models
from ..control import util as cutil
from . import base_opts, nemesis_cycle

DIR = "/opt/logcabin"
PIDFILE = f"{DIR}/logcabin.pid"
LOGFILE = f"{DIR}/logcabin.log"


class LogCabinDB(jdb.DB, jdb.LogFiles):
    """git + scons build, bootstrap on node 0, daemonize
    (install!/db, logcabin.clj:23-140)."""

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "git-core", "scons",
                  "g++", "protobuf-compiler", "libprotobuf-dev",
                  "libcrypto++-dev")
        sess.exec("sh", "-c",
                  f"test -d {DIR} || git clone "
                  f"https://github.com/logcabin/logcabin {DIR}")
        sess.exec("sh", "-c",
                  f"cd {DIR} && git submodule update --init && scons")
        nodes = test.get("nodes", [node])
        sid = nodes.index(node) + 1 if node in nodes else 1
        cfg = "\n".join([f"serverId = {sid}",
                         f"listenAddresses = {node}:5254",
                         f"storagePath = {DIR}/storage"])
        sess.exec("sh", "-c",
                  f"cat > {DIR}/logcabin.conf << 'EOF'\n{cfg}\nEOF")
        if node == nodes[0]:
            sess.exec(f"{DIR}/build/LogCabin",
                      "--config", f"{DIR}/logcabin.conf", "--bootstrap")
        cutil.start_daemon(
            sess, f"{DIR}/build/LogCabin",
            "--config", f"{DIR}/logcabin.conf",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/storage")

    def log_files(self, test, node):
        return [LOGFILE]


class LogCabinClient(jclient.Client):
    """Register ops via the `logcabin` CLI over SSH (write/read a tree
    path) — the reference shells out the same way for its smoke ops."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return LogCabinClient(node)

    def invoke(self, test, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        sess = control.session(test, self.node)
        cluster = ",".join(f"{n}:5254" for n in test.get("nodes", []))
        try:
            if op["f"] == "read":
                res = sess.exec_raw(
                    f"{DIR}/build/Examples/TreeOps "
                    f"--cluster={cluster} read /r{k} 2>/dev/null")
                out = res.out.strip()
                return {**op, "type": "ok",
                        "value": lift(int(out) if out else None)}
            if op["f"] == "write":
                sess.exec("sh", "-c",
                          f"echo {int(val)} | "
                          f"{DIR}/build/Examples/TreeOps "
                          f"--cluster={cluster} write /r{k}")
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except control.CommandError as e:
            return {**op, "type": "fail", "error": str(e)[:120]}
        except control.ConnectionError_ as e:
            crash = "fail" if op["f"] == "read" else "info"
            return {**op, "type": crash, "error": str(e)[:120]}
        finally:
            sess.disconnect()


def workloads(opts: dict | None = None) -> dict:
    from ..workloads.register import r, w

    def register():
        return {
            "generator": independent.concurrent_generator(
                2, range(10_000),
                lambda k: gen.limit(100, gen.mix([r, w]))),
            "checker": independent.checker(
                jchecker.linearizable(models.register())),
        }

    return {"register": register}


def logcabin_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wl = workloads(opts)["register"]()
    test = {
        "name": "logcabin register",
        "os": os_setup.debian(),
        "db": LogCabinDB(),
        "client": opts.get("client") or LogCabinClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": wl["checker"],
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(wl["generator"],
                        nemesis_cycle(opts.get("nemesis-interval", 10)))),
        "workload": "register",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: logcabin_test(tmap),
                        name="logcabin", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
