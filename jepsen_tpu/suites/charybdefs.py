"""CharybdeFS-analogue suite: disk-fault injection through faultfs.

Counterpart of charybdefs/src/jepsen/charybdefs.clj (85 LoC): mount a
fault-injecting FUSE filesystem, run file I/O through it while the
nemesis flips fault modes (break-all / break-one-percent / clear), and
assert the harness survives and classifies the failures. Our
filesystem is native/faultfs.cc driven by jepsen_tpu.faultfs; the
client does its file ops over the control session (SSH), like the
reference's exec-based probes.
"""

from __future__ import annotations

import itertools

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import faultfs
from .. import generator as gen
from .. import os_setup
from . import base_opts

MOUNT_FILE = f"{faultfs.MOUNT_DIR}/jepsen.log"


class FaultFSDB(jdb.DB):
    """Builds + mounts faultfs (install!, charybdefs.clj:41-65)."""

    def setup(self, test, node):
        faultfs.install(test, node)

    def teardown(self, test, node):
        faultfs.unmount(test, node)


class FileClient(jclient.Client):
    """Appends/reads lines through the faulty mount over the control
    session. Write failures under injected faults are expected and
    must surface as clean op-level fails, never harness crashes."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return FileClient(node)

    def invoke(self, test, op):
        sess = control.session(test, self.node)
        try:
            if op["f"] == "append":
                sess.exec("sh", "-c",
                          f"echo {int(op['value'])} >> {MOUNT_FILE}")
                return {**op, "type": "ok"}
            if op["f"] == "read":
                res = sess.exec_raw(f"cat {MOUNT_FILE} 2>/dev/null")
                vals = [int(x) for x in res.out.split() if x.strip()]
                return {**op, "type": "ok", "value": vals}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except control.CommandError as e:
            # EIO from the fault layer: a definite failure
            return {**op, "type": "fail", "error": str(e)[:120]}
        except control.ConnectionError_ as e:
            return {**op, "type": "info", "error": str(e)[:120]}
        finally:
            sess.disconnect()


def generator():
    counter = itertools.count()

    def append(test=None, ctx=None):
        return {"type": "invoke", "f": "append", "value": next(counter)}

    return gen.mix([append, gen.repeat_gen({"f": "read"})])


def charybdefs_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    test = {
        "name": "charybdefs file-faults",
        "os": os_setup.debian(),
        "db": FaultFSDB(),
        "client": opts.get("client") or FileClient(),
        "nemesis": faultfs.FaultFSNemesis(),
        "checker": jchecker.compose({
            "stats": jchecker.stats(),
            "exceptions": jchecker.unhandled_exceptions(),
        }),
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(
                generator(),
                gen.cycle([
                    gen.sleep(5),
                    {"type": "info", "f": "break-pct", "value": 0.01},
                    gen.sleep(5), {"type": "info", "f": "clear"},
                ]))),
        "workload": "file-faults",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def workloads(opts: dict | None = None) -> dict:
    return {"file-faults": lambda: {
        "generator": generator(),
        "checker": jchecker.stats()}}


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: charybdefs_test(tmap),
                        name="charybdefs", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
