"""Aerospike suite.

Counterpart of aerospike/src/jepsen/aerospike.clj (1,262 LoC, plus the
TLA+ spec at aerospike/spec/aerospike.tla — our model spec lives at
suites/specs/aerospike.tla and makes the lost-acked-write claim the
empirical register workload hunts): deb-installed server with a
mesh-seeded cluster, CAS-register (generation-check writes) and counter
workloads. The wire protocol is Aerospike's bespoke binary info/data
protocol — the client is pluggable (pass ``client`` in opts);
install/cluster/workload wiring is complete.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from . import base_opts, standard_workloads, suite_test

LOGFILE = "/var/log/aerospike/aerospike.log"


class AerospikeDB(jdb.DB, jdb.LogFiles):
    def __init__(self, version: str = "3.5.4"):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://www.aerospike.com/artifacts/aerospike-server-"
               f"community/{self.version}/aerospike-server-community-"
               f"{self.version}-debian7.tgz")
        sess.exec("sh", "-c",
                  f"wget -qO /tmp/aerospike.tgz {url} && "
                  f"tar -xzf /tmp/aerospike.tgz -C /tmp && "
                  f"dpkg -i /tmp/aerospike-server-community-*/"
                  f"aerospike-server-*.deb")
        nodes = test.get("nodes", [node])
        mesh = "\n".join(
            f"    mesh-seed-address-port {n} 3002" for n in nodes)
        cfg = ("service {\n  paxos-single-replica-limit 1\n}\n"
               "network {\n  service { address any\n port 3000 }\n"
               "  heartbeat {\n    mode mesh\n    port 3002\n"
               f"{mesh}\n    interval 150\n    timeout 10\n  }}\n}}\n"
               "namespace jepsen {\n  replication-factor 3\n"
               "  memory-size 1G\n  storage-engine memory\n}\n")
        sess.exec("sh", "-c",
                  f"cat > /etc/aerospike/aerospike.conf "
                  f"<< 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "aerospike", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "aerospike", "stop")

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {k: std[k] for k in ("register", "set", "monotonic")}


def aerospike_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    return suite_test(
        "aerospike", wname, opts, workloads(opts),
        db=AerospikeDB(opts.get("version", "3.5.4")),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: aerospike_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="aerospike",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
