"""Aerospike suite.

Counterpart of aerospike/src/jepsen/aerospike.clj (1,262 LoC, plus the
TLA+ spec at aerospike/spec/aerospike.tla — our model spec lives at
suites/specs/aerospike.tla and makes the lost-acked-write claim the
empirical register workload hunts): deb-installed server with a
mesh-seeded cluster, driven over the bespoke binary message protocol
(drivers/aerospike_msg.py) — CAS registers via generation-check writes
(cas_register.clj:43-90's AerospikeClient usage) and the server-side
INCR counter workload (counter.clj).
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent
from .. import nemesis as jnemesis, os_setup
from ..drivers import DriverError
from ..drivers import aerospike_msg as asp
from . import base_opts, standard_workloads, suite_test

LOGFILE = "/var/log/aerospike/aerospike.log"


class AerospikeDB(jdb.DB, jdb.LogFiles):
    def __init__(self, version: str = "3.5.4"):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://www.aerospike.com/artifacts/aerospike-server-"
               f"community/{self.version}/aerospike-server-community-"
               f"{self.version}-debian7.tgz")
        sess.exec("sh", "-c",
                  f"wget -qO /tmp/aerospike.tgz {url} && "
                  f"tar -xzf /tmp/aerospike.tgz -C /tmp && "
                  f"dpkg -i /tmp/aerospike-server-community-*/"
                  f"aerospike-server-*.deb")
        nodes = test.get("nodes", [node])
        mesh = "\n".join(
            f"    mesh-seed-address-port {n} 3002" for n in nodes)
        cfg = ("service {\n  paxos-single-replica-limit 1\n}\n"
               "network {\n  service { address any\n port 3000 }\n"
               "  heartbeat {\n    mode mesh\n    port 3002\n"
               f"{mesh}\n    interval 150\n    timeout 10\n  }}\n}}\n"
               "namespace jepsen {\n  replication-factor 3\n"
               "  memory-size 1G\n  storage-engine memory\n}\n")
        sess.exec("sh", "-c",
                  f"cat > /etc/aerospike/aerospike.conf "
                  f"<< 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "aerospike", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "aerospike", "stop")

    def log_files(self, test, node):
        return [LOGFILE]


class _AsClient(jclient.Client):
    port = 3000

    def __init__(self, conn: asp.AsConn | None = None,
                 port: int | None = None):
        self.conn = conn
        if port is not None:
            self.port = port

    def open(self, test, node):
        return type(self)(asp.AsConn(node, self.port), port=self.port)

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


class AerospikeCasClient(_AsClient):
    """CAS register: reads return {value, generation}; cas re-reads and
    writes with a generation check, so a concurrent update fails the
    cas (cas_register.clj's record-generation scheme)."""

    def invoke(self, test, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = ((lambda x: independent.tuple_(k, x))
                if independent.is_tuple(v) else (lambda x: x))
        try:
            if op["f"] == "read":
                rec = self.conn.get(k)
                out = None if rec is None else rec["bins"].get("value")
                return {**op, "type": "ok", "value": lift(out)}
            if op["f"] == "write":
                self.conn.put(k, {"value": val})
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = val
                rec = self.conn.get(k)
                if rec is None or rec["bins"].get("value") != old:
                    return {**op, "type": "fail", "error": "precond"}
                try:
                    self.conn.put(k, {"value": new},
                                  generation=rec["generation"])
                except asp.AerospikeError as e:
                    if e.code == asp.RESULT_GENERATION:
                        return {**op, "type": "fail",
                                "error": "generation-mismatch"}
                    raise
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": f"bad f {op['f']!r}"}
        except asp.AerospikeError as e:
            return {**op, "type": "fail", "error": str(e)[:120]}
        except DriverError as e:
            crash = "fail" if op["f"] == "read" else "info"
            return {**op, "type": crash, "error": str(e)[:120]}


class AerospikeCounterClient(_AsClient):
    """Server-side INCR counter (counter.clj): add deltas, read the
    running value; checked by the counter-bounds checker."""

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.conn.add("counter", "value", op["value"])
                return {**op, "type": "ok"}
            if op["f"] == "read":
                rec = self.conn.get("counter")
                out = 0 if rec is None else rec["bins"].get("value", 0)
                return {**op, "type": "ok", "value": out}
            return {**op, "type": "fail", "error": f"bad f {op['f']!r}"}
        except DriverError as e:
            crash = "fail" if op["f"] == "read" else "info"
            return {**op, "type": crash, "error": str(e)[:120]}
        except asp.AerospikeError as e:
            return {**op, "type": "fail", "error": str(e)[:120]}


def _counter_workload() -> dict:
    import random as _r

    def add(test=None, ctx=None):
        return {"type": "invoke", "f": "add", "value": _r.randint(1, 5)}

    return {
        "client": AerospikeCounterClient(),
        "generator": gen.stagger(1 / 10, gen.mix(
            [add, gen.repeat_gen({"type": "invoke", "f": "read"})])),
        "checker": jchecker.counter(),
    }


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {
        "register": lambda: {**std["register"](),
                             "client": AerospikeCasClient()},
        "counter": _counter_workload,
        "set": std["set"],
        "monotonic": std["monotonic"],
    }


def aerospike_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    return suite_test(
        "aerospike", wname, opts, workloads(opts),
        db=AerospikeDB(opts.get("version", "3.5.4")),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: aerospike_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="aerospike",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
