"""RobustIRC suite.

Counterpart of robustirc/src/jepsen/robustirc.clj (217 LoC + the
gencert.go TLS helper): a raft-replicated IRC network whose messages
must never be lost or reordered. RobustIRC clients speak HTTP+JSON
(robustsession protocol) to post and fetch messages; the suite wires a
message-set workload over it. TLS cert generation is handled by
openssl on-node instead of the reference's Go helper.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from ..workloads import queue as queue_wl
from . import base_opts, standard_workloads, suite_test

DIR = "/opt/robustirc"
PIDFILE = f"{DIR}/robustirc.pid"
LOGFILE = f"{DIR}/robustirc.log"


class RobustIRCDB(jdb.DB, jdb.LogFiles):
    """go install + self-signed cert + join node 0
    (db, robustirc.clj:40-110)."""

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "golang", "openssl")
        sess.exec("sh", "-c",
                  f"test -x {DIR}/robustirc || "
                  f"GOPATH={DIR}/go go install "
                  f"github.com/robustirc/robustirc@latest")
        sess.exec("mkdir", "-p", DIR)
        # self-signed cert (replaces resources/gencert.go)
        sess.exec("sh", "-c",
                  f"test -f {DIR}/cert.pem || openssl req -x509 "
                  f"-newkey rsa:2048 -keyout {DIR}/key.pem "
                  f"-out {DIR}/cert.pem -days 1 -nodes "
                  f"-subj /CN={node}")
        nodes = test.get("nodes", [node])
        args = [f"{DIR}/go/bin/robustirc",
                "-network_name", "jepsen",
                "-peer_addr", f"{node}:13001",
                "-tls_cert_path", f"{DIR}/cert.pem",
                "-tls_key_path", f"{DIR}/key.pem"]
        if node != nodes[0]:
            args += ["-join", f"{nodes[0]}:13001"]
        else:
            args += ["-singlenode"]
        cutil.start_daemon(sess, *args, logfile=LOGFILE,
                           pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    std = standard_workloads(opts)
    # message delivery == set semantics: every acknowledged message
    # must be in the final channel history
    return {"set": std["set"],
            "queue": lambda: queue_wl.test(opts.get("ops", 500))}


def robustirc_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "set")
    return suite_test(
        "robustirc", wname, opts, workloads(opts),
        db=RobustIRCDB(),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: robustirc_test(
            {**tmap, "workload": resolve_workload(args, tmap, "set")}),
        name="robustirc",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
