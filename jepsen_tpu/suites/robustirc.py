"""RobustIRC suite.

Counterpart of robustirc/src/jepsen/robustirc.clj (217 LoC + the
gencert.go TLS helper): a raft-replicated IRC network whose messages
must never be lost or reordered. The client speaks the robustsession
HTTP+JSON protocol directly (create session / post message / stream
messages) — each set-add is a PRIVMSG to the test channel, the final
read drains the channel backlog. TLS cert generation is handled by
openssl on-node instead of the reference's Go helper.
"""

from __future__ import annotations

import json
import socket
import ssl
import uuid
import urllib.error
import urllib.request

from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from ..workloads import queue as queue_wl
from . import base_opts, standard_workloads, suite_test
from .sql import resolve

DIR = "/opt/robustirc"
PIDFILE = f"{DIR}/robustirc.pid"
LOGFILE = f"{DIR}/robustirc.log"


class RobustIRCDB(jdb.DB, jdb.LogFiles):
    """go install + self-signed cert + join node 0
    (db, robustirc.clj:40-110)."""

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y", "golang", "openssl")
        sess.exec("sh", "-c",
                  f"test -x {DIR}/robustirc || "
                  f"GOPATH={DIR}/go go install "
                  f"github.com/robustirc/robustirc@latest")
        sess.exec("mkdir", "-p", DIR)
        # self-signed cert (replaces resources/gencert.go)
        sess.exec("sh", "-c",
                  f"test -f {DIR}/cert.pem || openssl req -x509 "
                  f"-newkey rsa:2048 -keyout {DIR}/key.pem "
                  f"-out {DIR}/cert.pem -days 1 -nodes "
                  f"-subj /CN={node}")
        nodes = test.get("nodes", [node])
        args = [f"{DIR}/go/bin/robustirc",
                "-network_name", "jepsen",
                "-peer_addr", f"{node}:13001",
                "-tls_cert_path", f"{DIR}/cert.pem",
                "-tls_key_path", f"{DIR}/key.pem"]
        if node != nodes[0]:
            args += ["-join", f"{nodes[0]}:13001"]
        else:
            args += ["-singlenode"]
        cutil.start_daemon(sess, *args, logfile=LOGFILE,
                           pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [LOGFILE]


CHANNEL = "#jepsen"


class RobustIRCClient(jclient.Client):
    """Set ops over the robustsession protocol
    (github.com/robustirc/robustirc: POST /robustirc/v1/session,
    POST .../message, GET .../messages), mirroring the reference's
    SetClient (robustirc.clj:150-180): add = `TOPIC #jepsen :v` (topic
    changes are broadcast to every member *including the setter*, so a
    reader sees its own adds — unlike PRIVMSG), read = drain the
    message stream and collect TOPIC payload ints."""

    def __init__(self, port: int = 13001, node: str | None = None,
                 timeout: float = 5.0, tls: bool = True):
        self.port = port
        self.node = node
        self.timeout = timeout
        self.tls = tls
        self.session = None        # (sessionid, sessionauth)
        if tls:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False   # self-signed per-test certs
            ctx.verify_mode = ssl.CERT_NONE
            self._sslctx = ctx
        else:
            self._sslctx = None

    def open(self, test, node):
        return RobustIRCClient(self.port, node, self.timeout, self.tls)

    def _ctx(self):
        return self._sslctx

    def _url(self, test, path: str) -> str:
        host, port = resolve(self.node, self.port, test or {})
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{host}:{port}/robustirc/v1{path}"

    def _request(self, test, path: str, body: dict | None = None,
                 method: str = "GET"):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._url(test, path), data=data, method=method,
            headers={"Content-Type": "application/json",
                     **({"X-Session-Auth": self.session[1]}
                        if self.session else {})})
        return urllib.request.urlopen(req, timeout=self.timeout,
                                      context=self._ctx())

    def _ensure_session(self, test):
        if self.session is None:
            with self._request(test, "/session", {}, "POST") as r:
                out = json.loads(r.read())
            self.session = (out["Sessionid"], out["Sessionauth"])
            for line in (f"NICK j{self.session[0][-6:]}",
                         f"USER jepsen 0 * :jepsen",
                         f"JOIN {CHANNEL}"):
                self._post_message(test, line)

    def _post_message(self, test, line: str) -> None:
        sid = self.session[0]
        self._request(test, f"/{sid}/message",
                      {"Data": line}, "POST").read()

    @staticmethod
    def _topic_payload(data: str) -> str | None:
        """IRC line -> TOPIC payload (filter-topic/extract-topic,
        robustirc.clj:138-148: second token is TOPIC for reflected
        lines, first for raw ones; payload after the last colon)."""
        toks = data.split()
        if len(toks) < 2 or ":" not in data:
            return None
        if toks[0] != "TOPIC" and toks[1] != "TOPIC":
            return None
        return data.rsplit(":", 1)[1].strip()

    def _drain_until(self, test, sentinel: str) -> tuple[list[int], bool]:
        """Stream ndjson messages, collecting TOPIC payload ints, until
        the sentinel topic is seen (-> complete backlog), the server
        closes, or the socket times out (-> partial)."""
        sid = self.session[0]
        vals = []
        complete = False
        try:
            with self._request(test, f"/{sid}/messages?lastseen=0.0"
                               ) as r:
                for raw in r:
                    try:
                        msg = json.loads(raw)
                    except json.JSONDecodeError:
                        continue
                    tail = self._topic_payload(msg.get("Data", ""))
                    if tail is None:
                        continue
                    if tail == sentinel:
                        complete = True
                        break
                    if tail.lstrip("-").isdigit():
                        vals.append(int(tail))
        except (TimeoutError, socket.timeout):
            pass  # long-poll stream: timeout ends the drain early
        return sorted(set(vals)), complete

    def invoke(self, test, op):
        crash = "fail" if op["f"] == "read" else "info"
        try:
            self._ensure_session(test)
            if op["f"] == "add":
                v = int(op["value"])
                self._post_message(test, f"TOPIC {CHANNEL} :{v}")
                return {**op, "type": "ok"}
            if op["f"] == "read":
                # A sentinel topic marks where the backlog ends: a
                # drain that never sees it is partial and must not be
                # reported as a definitive read (set-checker would
                # count committed adds as lost).
                sentinel = f"end-{uuid.uuid4().hex[:12]}"
                self._post_message(test, f"TOPIC {CHANNEL} :{sentinel}")
                seen, complete = self._drain_until(test, sentinel)
                if not complete:
                    return {**op, "type": "fail",
                            "error": "partial-backlog"}
                return {**op, "type": "ok", "value": seen}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except urllib.error.HTTPError as e:
            self.session = None
            return {**op, "type": "fail" if 400 <= e.code < 500
                    else crash, "error": f"http-{e.code}"}
        except OSError as e:
            self.session = None
            return {**op, "type": crash, "error": str(e)[:160]}


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    std = standard_workloads(opts)
    tls = opts.get("tls", True)

    def set_():
        # message delivery == set semantics: every acknowledged message
        # must be in the final channel history
        return {**std["set"](), "client": RobustIRCClient(tls=tls)}

    # queue has no robustsession client (IRC has no dequeue); it stays
    # pluggable via opts["client"]
    return {"set": set_,
            "queue": lambda: queue_wl.test(opts.get("ops", 500))}


def robustirc_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "set")
    return suite_test(
        "robustirc", wname, opts, workloads(opts),
        db=RobustIRCDB(),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: robustirc_test(
            {**tmap, "workload": resolve_workload(args, tmap, "set")}),
        name="robustirc",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
