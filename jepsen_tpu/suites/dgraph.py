"""Dgraph suite — part of config #5.

Counterpart of dgraph/src/jepsen/dgraph (SURVEY.md §2.6): zero + alpha
daemons and a matrix of bank, long-fork, linearizable-register,
sequential, set, and upsert (predicate uniqueness ≈ the adya G2
workload). Clients speak Dgraph's HTTP API when driven live; the
workload/checker matrix and analyze path are complete without a live
cluster.
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from ..drivers import DBError, DriverError
from . import base_opts, standard_workloads, suite_test
from .sql import resolve

VERSION = "v1.0.17"
DIR = "/opt/dgraph"


class DgraphDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """dgraph zero + alpha daemons (dgraph/src/jepsen/dgraph/support.clj);
    whole-node kill/pause via SignalProcess."""

    process_pattern = f"{DIR}/dgraph"

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://github.com/dgraph-io/dgraph/releases/download/"
               f"{self.version}/dgraph-linux-amd64.tar.gz")
        cutil.install_archive(sess, url, DIR)
        self._start(sess, test, node)

    def _start(self, sess, test, node):
        nodes = test.get("nodes", [])
        zero = nodes[0] if nodes else node
        if node == zero:
            cutil.start_daemon(
                sess, f"{DIR}/dgraph", "zero",
                "--my", f"{node}:5080",
                "--wal", f"{DIR}/zw",
                logfile=f"{DIR}/zero.log", pidfile=f"{DIR}/zero.pid",
                chdir=DIR)
        cutil.start_daemon(
            sess, f"{DIR}/dgraph", "alpha",
            "--my", f"{node}:7080",
            "--zero", f"{zero}:5080",
            "--postings", f"{DIR}/p", "--wal", f"{DIR}/w",
            logfile=f"{DIR}/alpha.log", pidfile=f"{DIR}/alpha.pid",
            chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        for pid in ("alpha.pid", "zero.pid"):
            cutil.stop_daemon(sess, f"{DIR}/{pid}")
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [f"{DIR}/zero.log", f"{DIR}/alpha.log"]


SCHEMA = """
key: int @index(int) .
val: int .
acct: int @index(int) .
balance: int .
el: int @index(int) .
skey: int @index(int) .
sval: int .
gkey: int @index(int) .
gside: string .
dkey: int @index(int) @upsert .
"""


class DgraphClient(jclient.Client):
    """Ops over Dgraph's HTTP transaction API (the reference uses the
    grpc client, dgraph/src/jepsen/dgraph/client.clj — same start_ts /
    commit dance, same conflict-aborts-map-to-fail semantics)."""

    def __init__(self, mode: str = "register", port: int = 8080,
                 accounts: list | None = None, total: int = 100,
                 node: str | None = None, timeout: float = 10.0):
        self.mode = mode
        self.port = port
        self.accounts = accounts if accounts is not None else list(range(8))
        self.total = total
        self.node = node
        self.timeout = timeout
        self.conn = None
        self._setup_done = False

    def open(self, test, node):
        return DgraphClient(self.mode, self.port, self.accounts,
                            self.total, node, self.timeout)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import dgraph_http
            host, port = resolve(self.node, self.port, test or {})
            self.conn = dgraph_http.connect(host, port, self.timeout)
        if not self._setup_done:
            self.conn.alter(SCHEMA)
            if self.mode == "bank":
                # conditional-upsert seed: insert only missing accounts
                for a in self.accounts:
                    bal = self.total if a == 0 else 0
                    self.conn.mutate(
                        query=f"{{ u as var(func: eq(acct, {int(a)})) }}",
                        cond="@if(eq(len(u), 0))",
                        set_obj=[{"uid": "_:new", "acct": int(a),
                                  "balance": bal}])
            self._setup_done = True

    def close(self, test):
        self.conn = None

    def invoke(self, test, op):
        read_only = op.get("f") == "read"
        try:
            self._ensure_conn(test)
            return self._dispatch(op)
        except DBError as e:
            # Definite failures: txn aborts (conflict) and 4xx
            # rejections. 5xx means the server may or may not have
            # applied the op — indeterminate for writes
            # (dgraph/client.clj's with-conflict-as-fail distinction).
            code = str(e.code)
            definite = (code == "ErrorAborted" or code.startswith("4")
                        or read_only)
            return {**op, "type": "fail" if definite else "info",
                    "error": f"dgraph-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    def _dispatch(self, op):
        if self.mode == "bank":
            return self._bank(op)
        if self.mode == "delete":
            return self._delete_ops(op)
        if self.mode == "set":
            return self._set(op)
        if self.mode in ("sequential", "causal-reverse"):
            return self._sequential(op)
        if self.mode == "wr":
            return self._wr_txn(op)
        if op.get("f") == "insert":
            return self._upsert_g2(op)
        return self._register(op)

    def _wr_txn(self, op):
        """[f k v] micro-op txns over key registers, one dgraph txn
        (long-fork / rw-register shapes)."""
        mops = op["value"]
        k0 = None
        if independent.is_tuple(mops):
            k0, mops = mops.key, mops.value
        txn = self.conn.begin()
        out_mops = []
        for mf, mk, mv in mops:
            if mf == "w":
                res = txn.query(
                    f"{{ q(func: eq(key, {int(mk)})) {{ uid }} }}")
                nodes = res.get("data", {}).get("q") or []
                uid = nodes[0]["uid"] if nodes else "_:new"
                txn.mutate(set_obj=[{"uid": uid, "key": int(mk),
                                     "val": int(mv)}])
                out_mops.append([mf, mk, mv])
            else:
                res = txn.query(
                    f"{{ q(func: eq(key, {int(mk)})) {{ val }} }}")
                vals = self._q_vals(res, "q", "val")
                out_mops.append([mf, mk,
                                 int(vals[0]) if vals else None])
        txn.commit()
        new_v = independent.tuple_(k0, out_mops) if k0 is not None \
            else out_mops
        return {**op, "type": "ok", "value": new_v}

    def _q_vals(self, out: dict, q: str, pred: str) -> list:
        return [n[pred] for n in (out.get("data", {}).get(q) or [])
                if pred in n]

    def _register(self, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        c = self.conn
        if op["f"] == "read":
            out = c.query(f"{{ q(func: eq(key, {int(k)})) {{ val }} }}")
            vals = self._q_vals(out, "q", "val")
            return {**op, "type": "ok",
                    "value": lift(int(vals[0]) if vals else None)}
        if op["f"] == "write":
            # conditional upsert: update the node when it exists, create
            # it when it doesn't — a bare uid(u) set with empty u is a
            # silent no-op in dgraph.
            c.mutate(
                query=f"{{ u as var(func: eq(key, {int(k)})) }}",
                mutations=[
                    {"cond": "@if(gt(len(u), 0))",
                     "set": [{"uid": "uid(u)", "key": int(k),
                              "val": int(val)}]},
                    {"cond": "@if(eq(len(u), 0))",
                     "set": [{"uid": "_:new", "key": int(k),
                              "val": int(val)}]},
                ])
            return {**op, "type": "ok"}
        if op["f"] == "cas":
            old, new = val
            txn = c.begin()
            out = txn.query(
                f"{{ q(func: eq(key, {int(k)})) {{ uid val }} }}")
            nodes = out.get("data", {}).get("q") or []
            cur = int(nodes[0]["val"]) if nodes and "val" in nodes[0] \
                else None
            if cur != old:
                txn.discard()
                return {**op, "type": "fail", "error": "precondition"}
            txn.mutate(set_obj=[{"uid": nodes[0]["uid"],
                                 "key": int(k), "val": int(new)}])
            txn.commit()  # conflict -> DBError -> fail
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _bank(self, op):
        c = self.conn
        if op["f"] == "read":
            out = c.query("{ q(func: has(acct)) { acct balance } }")
            nodes = out.get("data", {}).get("q") or []
            return {**op, "type": "ok",
                    "value": {int(n["acct"]): int(n["balance"])
                              for n in nodes}}
        if op["f"] == "transfer":
            t = op["value"]
            frm, to, amt = int(t["from"]), int(t["to"]), int(t["amount"])
            txn = c.begin()
            out = txn.query(
                f"{{ a(func: eq(acct, {frm})) {{ uid balance }} "
                f"b(func: eq(acct, {to})) {{ uid balance }} }}")
            a = (out.get("data", {}).get("a") or [None])[0]
            b = (out.get("data", {}).get("b") or [None])[0]
            if not a or not b or int(a["balance"]) < amt:
                txn.discard()
                return {**op, "type": "fail", "error": "insufficient"}
            txn.mutate(set_obj=[
                {"uid": a["uid"], "balance": int(a["balance"]) - amt},
                {"uid": b["uid"], "balance": int(b["balance"]) + amt}])
            txn.commit()
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _set(self, op):
        c = self.conn
        if op["f"] == "add":
            c.mutate(set_obj=[{"el": int(op["value"])}])
            return {**op, "type": "ok"}
        if op["f"] == "read":
            out = c.query("{ q(func: has(el)) { el } }")
            return {**op, "type": "ok",
                    "value": sorted(int(v) for v in
                                    self._q_vals(out, "q", "el"))}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _sequential(self, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        c = self.conn
        if op["f"] == "write":
            c.mutate(set_obj=[{"skey": int(k), "sval": int(val)}])
            return {**op, "type": "ok"}
        if op["f"] == "read":
            out = c.query(
                f"{{ q(func: eq(skey, {int(k)})) {{ sval }} }}")
            vals = sorted(int(x) for x in self._q_vals(out, "q", "sval"))
            return {**op, "type": "ok",
                    "value": independent.tuple_(k, vals)
                    if independent.is_tuple(v) else vals}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _delete_ops(self, op):
        """delete.clj:32-60: per-key upsert/delete/read against an
        indexed predicate; reads must see the index agree with the data
        (zero records, or exactly one {uid, key} record)."""
        v = op["value"]
        k = v.key if independent.is_tuple(v) else 0
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        c = self.conn
        if op["f"] == "read":
            out = c.query(
                f"{{ q(func: eq(dkey, {int(k)})) {{ uid dkey }} }}")
            nodes = out.get("data", {}).get("q") or []
            recs = [{"uid": n.get("uid"), "key": n.get("dkey")}
                    for n in nodes]
            return {**op, "type": "ok", "value": lift(recs)}
        if op["f"] == "upsert":
            txn = c.begin()
            out = txn.query(
                f"{{ q(func: eq(dkey, {int(k)})) {{ uid }} }}")
            if out.get("data", {}).get("q"):
                txn.discard()
                return {**op, "type": "fail", "error": "present"}
            txn.mutate(set_obj=[{"uid": "_:new", "dkey": int(k)}])
            txn.commit()  # conflict -> DBError ErrorAborted -> fail
            return {**op, "type": "ok"}
        if op["f"] == "delete":
            txn = c.begin()
            out = txn.query(
                f"{{ q(func: eq(dkey, {int(k)})) {{ uid }} }}")
            nodes = out.get("data", {}).get("q") or []
            if not nodes:
                txn.discard()
                return {**op, "type": "fail", "error": "not-found"}
            txn.mutate(delete_obj=[{"uid": nodes[0]["uid"]}])
            txn.commit()
            return {**op, "type": "ok", "uid": nodes[0]["uid"]}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _upsert_g2(self, op):
        v = op["value"]
        k, pair = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        a_id, b_id = pair
        side = "a" if a_id is not None else "b"
        txn = self.conn.begin()
        out = txn.query(
            f"{{ q(func: eq(gkey, {int(k)})) {{ uid }} }}")
        if out.get("data", {}).get("q"):
            txn.discard()
            return {**op, "type": "fail", "error": "already-present"}
        txn.mutate(set_obj=[{"gkey": int(k), "gside": side}])
        txn.commit()  # write-write conflict on gkey -> abort -> fail
        return {**op, "type": "ok"}


class DeleteChecker(jchecker.Checker):
    """delete.clj:66-90: every ok read finds either nothing, or exactly
    one record carrying both a uid and the key under test — anything
    else (ghost records, index/data divergence, half-deleted nodes) is
    a bad read."""

    def check(self, test, history, opts):
        k = (opts or {}).get("history-key")
        bad = []
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            recs = op.get("value")
            if not isinstance(recs, (list, tuple)):
                bad.append(op)
                continue
            if len(recs) == 0:
                continue
            r0 = recs[0] if isinstance(recs[0], dict) else {}
            if (len(recs) == 1 and set(r0) == {"uid", "key"}
                    and r0["uid"] and (k is None or r0["key"] == k)):
                continue
            bad.append(op)
        return {"valid?": not bad, "bad-reads": bad[:16],
                "bad-count": len(bad)}


def delete_workload(opts: dict) -> dict:
    """delete.clj:92-104: independent per-key concurrent generator over
    a mix of read/upsert/delete, checked per key."""
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]

    def r(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def u(test=None, ctx=None):
        return {"type": "invoke", "f": "upsert", "value": None}

    def d(test=None, ctx=None):
        return {"type": "invoke", "f": "delete", "value": None}

    return {
        "generator": independent.concurrent_generator(
            2 * len(nodes), range(10_000),
            lambda k: gen.stagger(
                0.01, gen.limit(1000, gen.mix([r, u, d])))),
        "checker": independent.checker(jchecker.compose({
            "deletes": DeleteChecker()})),
    }


#: workload -> client mode
MODES = {"register": "register", "bank": "bank", "set": "set",
         "sequential": "sequential", "upsert": "g2", "long-fork": "wr",
         "delete": "delete"}


def default_client(workload: str, opts: dict) -> DgraphClient:
    return DgraphClient(MODES.get(workload, "register"),
                        accounts=opts.get("accounts"),
                        total=opts.get("total-amount", 100))


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {
        "bank": std["bank"],
        "long-fork": std["long-fork"],
        "register": std["register"],      # linearizable-register
        "sequential": std["sequential"],
        "set": std["set"],
        "upsert": std["g2"],              # predicate-uniqueness races
        "delete": lambda: delete_workload(opts or {}),
    }


def dgraph_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "bank")
    return suite_test(
        "dgraph", wname, opts, workloads(opts),
        db=DgraphDB(opts.get("version", VERSION)),
        client=opts.get("client") or default_client(wname, opts),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: dgraph_test(
            {**tmap, "workload": resolve_workload(args, tmap, "bank")}),
        name="dgraph",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        tests_fn=lambda tmap, args: [
            dgraph_test({**tmap, "workload": w})
            for w in ([args.workload] if getattr(
                args, "workload", None) else sorted(workloads()))],
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
