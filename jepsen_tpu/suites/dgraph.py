"""Dgraph suite — part of config #5.

Counterpart of dgraph/src/jepsen/dgraph (SURVEY.md §2.6): zero + alpha
daemons and a matrix of bank, long-fork, linearizable-register,
sequential, set, and upsert (predicate uniqueness ≈ the adya G2
workload). Clients speak Dgraph's HTTP API when driven live; the
workload/checker matrix and analyze path are complete without a live
cluster.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, standard_workloads, suite_test

VERSION = "v1.0.17"
DIR = "/opt/dgraph"


class DgraphDB(jdb.DB, jdb.LogFiles):
    """dgraph zero + alpha daemons (dgraph/src/jepsen/dgraph/support.clj)."""

    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://github.com/dgraph-io/dgraph/releases/download/"
               f"{self.version}/dgraph-linux-amd64.tar.gz")
        cutil.install_archive(sess, url, DIR)
        nodes = test.get("nodes", [])
        zero = nodes[0] if nodes else node
        if node == zero:
            cutil.start_daemon(
                sess, f"{DIR}/dgraph", "zero",
                "--my", f"{node}:5080",
                "--wal", f"{DIR}/zw",
                logfile=f"{DIR}/zero.log", pidfile=f"{DIR}/zero.pid",
                chdir=DIR)
        cutil.start_daemon(
            sess, f"{DIR}/dgraph", "alpha",
            "--my", f"{node}:7080",
            "--zero", f"{zero}:5080",
            "--postings", f"{DIR}/p", "--wal", f"{DIR}/w",
            logfile=f"{DIR}/alpha.log", pidfile=f"{DIR}/alpha.pid",
            chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        for pid in ("alpha.pid", "zero.pid"):
            cutil.stop_daemon(sess, f"{DIR}/{pid}")
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [f"{DIR}/zero.log", f"{DIR}/alpha.log"]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {
        "bank": std["bank"],
        "long-fork": std["long-fork"],
        "register": std["register"],      # linearizable-register
        "sequential": std["sequential"],
        "set": std["set"],
        "upsert": std["g2"],              # predicate-uniqueness races
    }


def dgraph_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    return suite_test(
        "dgraph", opts.get("workload", "bank"), opts, workloads(opts),
        db=DgraphDB(opts.get("version", VERSION)),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: dgraph_test(
            {**tmap, "workload": resolve_workload(args, tmap, "bank")}),
        name="dgraph",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        tests_fn=lambda tmap, args: [
            dgraph_test({**tmap, "workload": w})
            for w in ([args.workload] if getattr(
                args, "workload", None) else sorted(workloads()))],
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
