"""MongoDB suite core: replica-set install + document-CAS clients.

Counterpart of the mongodb-rocks and mongodb-smartos suites
(mongodb-rocks/src/jepsen/mongodb_rocks.clj 169 LoC — a storage-engine
variant; mongodb-smartos 788 LoC — an OS variant). Both share this
module's DB (tarball mongod, one replica set, rs.initiate from node 0
over the wire protocol) and client (findAndModify document CAS with
majority write concern, majority-read register reads).
"""

from __future__ import annotations

import random

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent, nemesis as jnemesis, os_setup
from ..checker import models
from ..control import util as cutil
from ..drivers import DBError, DriverError
from ..workloads import set_workload
from . import base_opts, nemesis_cycle
from .sql import resolve

VERSION = "3.4.1"
DIR = "/opt/mongodb"
PIDFILE = f"{DIR}/mongod.pid"
LOGFILE = f"{DIR}/mongod.log"
PORT = 27017
RS = "jepsen"

MAJORITY = {"w": "majority"}


class MongoDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """Tarball mongod with --replSet; node 0 initiates the set over
    the wire protocol once every member is up. kill/pause fault
    protocols via SignalProcess."""

    process_pattern = "mongod"

    def __init__(self, version: str = VERSION,
                 storage_engine: str = "wiredTiger"):
        self.version = version
        self.storage_engine = storage_engine

    def _start(self, sess, test, node):
        cutil.start_daemon(
            sess, f"{DIR}/bin/mongod",
            "--dbpath", f"{DIR}/data",
            "--bind_ip", node,
            "--port", str(PORT),
            "--replSet", RS,
            "--storageEngine", self.storage_engine,
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://fastdl.mongodb.org/linux/"
               f"mongodb-linux-x86_64-{self.version}.tgz")
        cutil.install_archive(sess, url, DIR)
        sess.exec("mkdir", "-p", f"{DIR}/data")
        self._start(sess, test, node)
        nodes = test.get("nodes", [node])
        dummy = bool(test.get("ssh", {}).get("dummy"))
        if node == nodes[0] and not dummy:
            # Setups run in parallel across nodes — retry until every
            # member answers (a fixed sleep races the slowest install;
            # mongod rejects replSetInitiate until peers are up).
            import time

            from ..drivers import DriverError, mongo
            members = [{"_id": i, "host": f"{n}:{PORT}"}
                       for i, n in enumerate(nodes)]
            last: Exception | None = None
            for _ in range(60):
                try:
                    conn = mongo.connect(node, PORT, database="admin")
                    try:
                        conn.command({"replSetInitiate":
                                      {"_id": RS, "members": members}})
                        return
                    finally:
                        conn.close()
                except DBError as e:
                    if "already initialized" in e.message:
                        return
                    last = e
                except (DriverError, OSError) as e:
                    last = e
                time.sleep(1)
            raise RuntimeError(f"replSetInitiate never succeeded: {last}")

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [LOGFILE]


class MongoClient(jclient.Client):
    """Document CAS register (the reference's findAndModify shape) and
    set-adds, all with majority write concern."""

    def __init__(self, mode: str = "register", port: int = PORT,
                 node: str | None = None, timeout: float = 5.0):
        self.mode = mode
        self.port = port
        self.node = node
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        return MongoClient(self.mode, self.port, node, self.timeout)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import mongo
            host, port = resolve(self.node, self.port, test or {})
            self.conn = mongo.connect(host, port, database="jepsen",
                                      timeout=self.timeout)

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def invoke(self, test, op):
        read_only = op["f"] == "read"
        try:
            self._ensure_conn(test)
            if self.mode == "set":
                return self._set(op)
            return self._register(op)
        except DBError as e:
            return {**op, "type": "fail",
                    "error": f"mongo-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    def _register(self, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        c = self.conn
        if op["f"] == "read":
            docs = c.find("registers", {"_id": int(k)},
                          read_concern={"level": "majority"})
            out = docs[0].get("value") if docs else None
            return {**op, "type": "ok", "value": lift(out)}
        if op["f"] == "write":
            c.update("registers", {"_id": int(k)},
                     {"$set": {"value": int(val)}}, upsert=True,
                     write_concern=MAJORITY)
            return {**op, "type": "ok"}
        if op["f"] == "cas":
            old, new = val
            reply = c.find_and_modify(
                "registers", {"_id": int(k), "value": int(old)},
                {"$set": {"value": int(new)}},
                write_concern=MAJORITY)
            if reply.get("value") is None:
                return {**op, "type": "fail", "error": "precondition"}
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _set(self, op):
        c = self.conn
        if op["f"] == "add":
            c.insert("sets", [{"_id": int(op["value"])}],
                     write_concern=MAJORITY)
            return {**op, "type": "ok"}
        if op["f"] == "read":
            docs = c.find("sets", {},
                          read_concern={"level": "majority"})
            return {**op, "type": "ok",
                    "value": sorted(int(d["_id"]) for d in docs)}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}


def r(test=None, ctx=None):
    return {"type": "invoke", "f": "read", "value": None}


def w(test=None, ctx=None):
    return {"type": "invoke", "f": "write", "value": random.randint(0, 4)}


def cas(test=None, ctx=None):
    return {"type": "invoke", "f": "cas",
            "value": [random.randint(0, 4), random.randint(0, 4)]}


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}

    def register():
        return {
            "generator": independent.concurrent_generator(
                2, range(10_000),
                lambda k: gen.limit(100, gen.mix([r, w, cas]))),
            "checker": independent.checker(
                jchecker.linearizable(models.cas_register())),
            "client": MongoClient("register"),
        }

    def set_():
        wl = set_workload.test(n=opts.get("set-size", 500))
        return {**wl, "client": MongoClient("set")}

    return {"register": register, "set": set_}


def mongodb_test(opts: dict | None = None, name: str = "mongodb",
                 storage_engine: str = "wiredTiger",
                 os_module=None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    wl = workloads(opts)[wname]()
    test = {
        "name": f"{name} {wname}",
        "os": os_module or os_setup.debian(),
        "db": MongoDB(opts.get("version", VERSION), storage_engine),
        "client": opts.get("client") or wl["client"],
        "nemesis": jnemesis.partition_random_halves(),
        "checker": wl["checker"],
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(wl["generator"],
                        nemesis_cycle(opts.get("nemesis-interval", 10)))),
        "workload": wname,
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: mongodb_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="mongodb",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
