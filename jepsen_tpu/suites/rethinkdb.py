"""RethinkDB suite.

Counterpart of rethinkdb/src/jepsen/rethinkdb (529 LoC): apt-installed
RethinkDB with a joined cluster, document CAS over write_acks=majority
tables. ReQL is a bespoke term-tree protocol spoken by the official
driver; the client here is pluggable (pass ``client`` in opts) while
install/cluster/workload wiring is complete.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from . import base_opts, standard_workloads, suite_test

LOGFILE = "/var/log/rethinkdb.log"


class RethinkDB(jdb.DB, jdb.LogFiles):
    """apt repo + service, joining node 0 (install!/start!,
    rethinkdb.clj:52-100)."""

    def __init__(self, version: str = "2.3.4~0jessie"):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("sh", "-c",
                  "wget -qO- https://download.rethinkdb.com/apt/"
                  "pubkey.gpg | apt-key add -")
        sess.exec("sh", "-c",
                  'echo "deb https://download.rethinkdb.com/apt '
                  'jessie main" > /etc/apt/sources.list.d/rethinkdb.list')
        sess.exec("apt-get", "update")
        sess.exec("apt-get", "install", "-y",
                  f"rethinkdb={self.version}")
        nodes = test.get("nodes", [node])
        cfg = "\n".join([f"bind=all", f"server-name={node}",
                         f"join={nodes[0]}:29015"])
        sess.exec("sh", "-c",
                  f"cat > /etc/rethinkdb/instances.d/jepsen.conf "
                  f"<< 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "rethinkdb", "start")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "rethinkdb", "stop")
        sess.exec("rm", "-rf", "/var/lib/rethinkdb")

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {k: std[k] for k in ("register", "set", "bank")}


def rethinkdb_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    return suite_test(
        "rethinkdb", wname, opts, workloads(opts),
        db=RethinkDB(opts.get("version", "2.3.4~0jessie")),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: rethinkdb_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="rethinkdb",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
