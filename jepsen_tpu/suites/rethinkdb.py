"""RethinkDB suite.

Counterpart of rethinkdb/src/jepsen/rethinkdb (529 LoC): apt-installed
RethinkDB with a joined cluster, driven over the ReQL wire protocol
directly (drivers.reql — V1_0 SCRAM handshake + JSON term queries)
with hard durability and majority reads, the write_acks=majority shape
the reference tests.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import independent, nemesis as jnemesis, os_setup
from ..checker import models
from ..drivers import DBError, DriverError
from ..workloads import set_workload
from . import base_opts, suite_test
from .sql import resolve

LOGFILE = "/var/log/rethinkdb.log"


class RethinkDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """apt repo + service, joining node 0 (install!/start!,
    rethinkdb.clj:52-100); kill/pause fault protocols via
    SignalProcess."""

    process_pattern = "rethinkdb"

    def __init__(self, version: str = "2.3.4~0jessie"):
        self.version = version

    def _start(self, sess, test, node):
        sess.exec("service", "rethinkdb", "start")

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("sh", "-c",
                  "wget -qO- https://download.rethinkdb.com/apt/"
                  "pubkey.gpg | apt-key add -")
        sess.exec("sh", "-c",
                  'echo "deb https://download.rethinkdb.com/apt '
                  'jessie main" > /etc/apt/sources.list.d/rethinkdb.list')
        sess.exec("apt-get", "update")
        sess.exec("apt-get", "install", "-y",
                  f"rethinkdb={self.version}")
        nodes = test.get("nodes", [node])
        cfg = "\n".join([f"bind=all", f"server-name={node}",
                         f"join={nodes[0]}:29015"])
        sess.exec("sh", "-c",
                  f"cat > /etc/rethinkdb/instances.d/jepsen.conf "
                  f"<< 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "rethinkdb", "start")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "rethinkdb", "stop")
        sess.exec("rm", "-rf", "/var/lib/rethinkdb")

    def log_files(self, test, node):
        return [LOGFILE]


DB_NAME = "jepsen"


class RethinkClient(jclient.Client):
    """Document ops over ReQL: reads are majority-read GETs, writes are
    hard-durability inserts with conflict replace — the write-then-
    read-your-majority shape the reference's register workload uses.
    (CAS needs ReQL lambda terms; the reference sweeps r/w too.)"""

    def __init__(self, mode: str = "register", port: int = 28015,
                 node: str | None = None, timeout: float = 5.0):
        self.mode = mode
        self.port = port
        self.node = node
        self.timeout = timeout
        self.conn = None
        self._setup_done = False

    def open(self, test, node):
        return RethinkClient(self.mode, self.port, node, self.timeout)

    def _ensure_conn(self, test):
        if self.conn is None:
            from ..drivers import reql
            host, port = resolve(self.node, self.port, test or {})
            self.conn = reql.connect(host, port, timeout=self.timeout)
        if not self._setup_done:
            self.conn.db_create(DB_NAME)
            for tbl in ("registers", "sets"):
                self.conn.table_create(DB_NAME, tbl)
            self._setup_done = True

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def invoke(self, test, op):
        read_only = op.get("f") == "read"
        try:
            self._ensure_conn(test)
            if self.mode == "set":
                return self._set(op)
            return self._register(op)
        except DBError as e:
            return {**op, "type": "fail",
                    "error": f"reql-{e.code}: {e.message[:120]}"}
        except (DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    def _register(self, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        c = self.conn
        if op["f"] == "read":
            doc = c.get(DB_NAME, "registers", int(k))
            out = doc.get("val") if isinstance(doc, dict) else None
            return {**op, "type": "ok", "value": lift(out)}
        if op["f"] == "write":
            c.insert(DB_NAME, "registers",
                     {"id": int(k), "val": int(val)},
                     conflict="replace", durability="hard")
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    def _set(self, op):
        c = self.conn
        if op["f"] == "add":
            c.insert(DB_NAME, "sets", {"id": int(op["value"])},
                     conflict="error", durability="hard")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            docs = c.run(c.table(DB_NAME, "sets"),
                         {"read_mode": "majority"})
            return {**op, "type": "ok",
                    "value": sorted(int(d["id"]) for d in docs)}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    from ..workloads import register as register_wl
    from ..workloads.register import r, w

    def register():
        # cas-less mix: ReQL updates are last-write-wins documents
        return {
            "generator": register_wl.generator(2, 10_000, 100,
                                               ops=[r, w]),
            "checker": register_wl.checker(model=models.register()),
            "client": RethinkClient("register"),
        }

    def set_():
        wl = set_workload.test(n=opts.get("set-size", 500))
        return {**wl, "client": RethinkClient("set")}

    return {"register": register, "set": set_}


def rethinkdb_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    return suite_test(
        "rethinkdb", wname, opts, workloads(opts),
        db=RethinkDB(opts.get("version", "2.3.4~0jessie")),
        client=opts.get("client"),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: rethinkdb_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="rethinkdb",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
