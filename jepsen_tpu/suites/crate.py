"""CrateDB suite.

Counterpart of crate/src/jepsen/crate/ (core + dirty_read +
lost_updates + version_divergence, 1,060 LoC): a tarball-installed
Crate cluster driven over its PostgreSQL wire port (5432 — the same
pg-wire driver the cockroach suite uses; the reference goes through
Crate's JDBC). dirty-read maps onto the shared register matrix;
version-divergence and lost-updates are implemented natively below
(version_divergence.clj:29-140, lost_updates.clj:32-148): both pivot
on Crate's `_version` system column — one value per row version, and
optimistic concurrency via `WHERE ... AND _version = ?`.
"""

from __future__ import annotations

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, sql, standard_workloads, suite_test

VERSION = "0.57.4"
DIR = "/opt/crate"
PIDFILE = f"{DIR}/crate.pid"
LOGFILE = f"{DIR}/logs/crate.log"


class CrateDB(jdb.DB, jdb.LogFiles):
    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://cdn.crate.io/downloads/releases/"
               f"crate-{self.version}.tar.gz")
        cutil.install_archive(sess, url, DIR)
        nodes = test.get("nodes", [node])
        hosts = ",".join(f"{n}:4300" for n in nodes)
        cutil.start_daemon(
            sess, f"{DIR}/bin/crate",
            f"-Cnode.name={node}",
            f"-Cnetwork.host={node}",
            f"-Cdiscovery.seed_hosts={hosts}",
            f"-Ccluster.initial_master_nodes={nodes[0]}",
            "-Cpsql.enabled=true",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [LOGFILE]


class CrateClient(jclient.Client):
    """_version-based ops over the pg wire (shared with the SQL
    machinery's drivers): version-divergence reads (value, _version)
    pairs per key; lost-updates does read-modify-write adds guarded by
    `AND _version = ?` — a 0-rowcount update is a definite CAS failure
    (lost_updates.clj:73-98)."""

    def __init__(self, mode: str, dialect: sql.Dialect | None = None,
                 node: str | None = None):
        self.mode = mode
        self.dialect = dialect or sql.PGDialect(port=5432, user="crate",
                                                database="doc")
        self.node = node
        self.conn = None
        self._setup_done = False

    def open(self, test, node):
        return CrateClient(self.mode, self.dialect, node)

    def _ensure_conn(self, test):
        if self.conn is None:
            self.conn = self.dialect.connect(self.node, test or {})
        if not self._setup_done:
            self.conn.query(
                "CREATE TABLE IF NOT EXISTS registers"
                " (id BIGINT PRIMARY KEY, val BIGINT)")
            self.conn.query(
                "CREATE TABLE IF NOT EXISTS lu_sets"
                " (id BIGINT PRIMARY KEY, elements TEXT)")
            self._setup_done = True

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def invoke(self, test, op):
        read_only = op.get("f") == "read"
        try:
            self._ensure_conn(test)
            return self._dispatch(op)
        except sql.DBError as e:
            ambiguous = str(e.code) in sql.AMBIGUOUS_SQL and not read_only
            return {**op, "type": "info" if ambiguous else "fail",
                    "error": f"crate-{e.code}: {e.message[:120]}"}
        except (sql.DriverError, OSError) as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    # space-separated int lists keep the elements column trivially
    # parseable on both ends (the reference round-trips JSON arrays)
    @staticmethod
    def _els_load(s) -> list[int]:
        return [int(x) for x in str(s or "").split()]

    @staticmethod
    def _els_dump(els: list[int]) -> str:
        return " ".join(str(x) for x in els)

    def _dispatch(self, op):
        kv = op["value"]
        k, v = (kv.key, kv.value) if independent.is_tuple(kv) \
            else (0, kv)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(kv) else (lambda x: x)
        c = self.conn
        if self.mode == "version-divergence":
            if op["f"] == "read":
                rows = sql._rows(c.query(
                    f'SELECT val, "_version" FROM registers '
                    f'WHERE id = {int(k)}'))
                out = None if not rows else \
                    {"value": int(rows[0][0]), "version": int(rows[0][1])}
                return {**op, "type": "ok", "value": lift(out)}
            if op["f"] == "write":
                c.query(self.dialect.upsert("registers", int(k), "val",
                                            str(int(v))))
                return {**op, "type": "ok"}
        if self.mode == "lost-updates":
            if op["f"] == "read":
                rows = sql._rows(c.query(
                    f"SELECT elements FROM lu_sets WHERE id = {int(k)}"))
                els = self._els_load(rows[0][0]) if rows else []
                return {**op, "type": "ok", "value": lift(sorted(els))}
            if op["f"] == "add":
                rows = sql._rows(c.query(
                    f'SELECT elements, "_version" FROM lu_sets '
                    f'WHERE id = {int(k)}'))
                if rows:
                    els = self._els_load(rows[0][0]) + [int(v)]
                    ver = int(rows[0][1])
                    res = c.query(
                        f"UPDATE lu_sets SET elements = "
                        f"'{self._els_dump(els)}' WHERE id = {int(k)} "
                        f"AND _version = {ver}")
                    n = _rowcount(res)
                    if n == 1:
                        return {**op, "type": "ok"}
                    if n == 0:   # version moved: CAS definitely lost
                        return {**op, "type": "fail",
                                "error": "version-conflict"}
                    return {**op, "type": "info",
                            "error": f"updated {n} rows!?"}
                try:
                    c.query(f"INSERT INTO lu_sets (id, elements) VALUES "
                            f"({int(k)}, '{self._els_dump([int(v)])}')")
                except sql.DBError as e:
                    if str(e.code) == "23505":   # concurrent create
                        return {**op, "type": "fail",
                                "error": "concurrent-create"}
                    raise
                return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}


def _rowcount(res) -> int:
    """Rows affected, from the driver's command tag ('UPDATE 1')."""
    tags = [r.tag for r in res] if isinstance(res, list) else [res.tag]
    for t in reversed(tags):
        parts = (t or "").split()
        if parts and parts[-1].isdigit():
            return int(parts[-1])
    return 0


class MultiVersionChecker(jchecker.Checker):
    """version_divergence.clj:94-108: every observed (_version ->
    value) binding must be functional — two reads of one version with
    different values mean divergent replicas served the same version
    number."""

    def check(self, test, history, opts):
        by_version: dict = {}
        for o in history:
            if o.get("type") != "ok" or o.get("f") != "read":
                continue
            val = o.get("value")
            if not isinstance(val, dict) or val.get("version") is None:
                continue
            by_version.setdefault(val["version"], set()).add(val["value"])
        multis = {ver: sorted(vals) for ver, vals in by_version.items()
                  if len(vals) > 1}
        return {"valid?": not multis, "multis": multis,
                "version-count": len(by_version)}


def _incrementing_writes(f: str = "write"):
    """Per-key unique ascending values (version_divergence.clj:111-114
    / lost_updates.clj:106-109's iterate-inc writer)."""
    import itertools
    counter = itertools.count()

    def w(test=None, ctx=None):
        return {"type": "invoke", "f": f, "value": next(counter)}

    return w


def version_divergence_gen(opts: dict) -> gen.Generator:
    keys = range(int(opts.get("key-count", 100000)))
    return independent.concurrent_generator(
        int(opts.get("keys-concurrent", 10)), keys,
        lambda k: gen.reserve(
            int(opts.get("readers", 5)),
            gen.repeat_gen({"f": "read", "value": None}),
            _incrementing_writes()))


def lost_updates_gen(opts: dict) -> gen.Generator:
    """Per-key phases (lost_updates.clj:126-136): a burst of guarded
    adds, quiescence, then one final read per worker."""
    tl = float(opts.get("time-limit", 60))
    quiesce = float(opts.get("quiesce", 5))
    keys = range(int(opts.get("key-count", 100000)))
    # adds stop a second before the outer time limit minus quiescence,
    # so the final reads land INSIDE the suite's time_limit wrapper
    adds_window = max(0.5, tl - quiesce - 1.0)
    return independent.concurrent_generator(
        int(opts.get("keys-concurrent", 10)), keys,
        lambda k: gen.phases(
            gen.time_limit(adds_window,
                           gen.delay(0.01, _incrementing_writes("add"))),
            gen.sleep(quiesce),
            gen.each_thread(gen.once({"f": "read", "value": None}))))


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    std = standard_workloads(opts)
    out = {k: std[k] for k in
           ("register", "set", "wr", "monotonic", "long-fork")}
    out["version-divergence"] = lambda: {
        "client": CrateClient("version-divergence"),
        "generator": version_divergence_gen(opts),
        "checker": independent.checker(MultiVersionChecker()),
    }
    out["lost-updates"] = lambda: {
        "client": CrateClient("lost-updates"),
        "generator": lost_updates_gen(opts),
        "checker": independent.checker(jchecker.set_checker()),
    }
    return out


def default_client(workload: str, opts: dict):
    return sql.client_for(
        sql.PGDialect(port=5432, user="crate", database="doc"),
        workload, opts)


def crate_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    # the _version workloads carry their own client; suite_test falls
    # back to wl["client"] when the explicit argument is None
    client = opts.get("client") or (
        default_client(wname, opts)
        if wname not in ("version-divergence", "lost-updates") else None)
    return suite_test(
        "crate", wname, opts, workloads(opts),
        db=CrateDB(opts.get("version", VERSION)),
        client=client,
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: crate_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="crate",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
