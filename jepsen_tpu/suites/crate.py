"""CrateDB suite.

Counterpart of crate/src/jepsen/crate/ (core + dirty_read +
lost_updates + version_divergence, 1,060 LoC): a tarball-installed
Crate cluster driven over its PostgreSQL wire port (5432 — the same
pg-wire driver the cockroach suite uses; the reference goes through
Crate's JDBC). The reference's anomaly hunts map onto the shared
matrix: dirty-read ≈ register, lost-updates ≈ monotonic/wr,
version-divergence ≈ long-fork.
"""

from __future__ import annotations

from .. import cli as jcli
from .. import control
from .. import db as jdb
from .. import nemesis as jnemesis, os_setup
from ..control import util as cutil
from . import base_opts, sql, standard_workloads, suite_test

VERSION = "0.57.4"
DIR = "/opt/crate"
PIDFILE = f"{DIR}/crate.pid"
LOGFILE = f"{DIR}/logs/crate.log"


class CrateDB(jdb.DB, jdb.LogFiles):
    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://cdn.crate.io/downloads/releases/"
               f"crate-{self.version}.tar.gz")
        cutil.install_archive(sess, url, DIR)
        nodes = test.get("nodes", [node])
        hosts = ",".join(f"{n}:4300" for n in nodes)
        cutil.start_daemon(
            sess, f"{DIR}/bin/crate",
            f"-Cnode.name={node}",
            f"-Cnetwork.host={node}",
            f"-Cdiscovery.seed_hosts={hosts}",
            f"-Ccluster.initial_master_nodes={nodes[0]}",
            "-Cpsql.enabled=true",
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [LOGFILE]


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {k: std[k] for k in
            ("register", "set", "wr", "monotonic", "long-fork")}


def default_client(workload: str, opts: dict):
    return sql.client_for(
        sql.PGDialect(port=5432, user="crate", database="doc"),
        workload, opts)


def crate_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wname = opts.get("workload", "register")
    return suite_test(
        "crate", wname, opts, workloads(opts),
        db=CrateDB(opts.get("version", VERSION)),
        client=opts.get("client") or default_client(wname, opts),
        nemesis=jnemesis.partition_random_halves(),
        os_setup=os_setup.debian())


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: crate_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="crate",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
