"""Consul suite.

Counterpart of consul/src/jepsen/consul (db.clj's binary install +
`consul agent -server`, client.clj's HTTP KV get/put/cas where CAS
rides the key's ModifyIndex, register.clj's linearizable register
workload). urllib is the whole client — consul's KV API is plain HTTP.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent, nemesis as jnemesis, os_setup
from ..checker import models
from ..control import util as cutil
from . import base_opts, nemesis_cycle
from .sql import resolve

VERSION = "0.5.2"
DIR = "/opt/consul"
BINARY = f"{DIR}/consul"
PIDFILE = f"{DIR}/consul.pid"
LOGFILE = f"{DIR}/consul.log"
DATA_DIR = f"{DIR}/data"


class ConsulDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """Zip install + `consul agent -server` with node 0 bootstrapping
    and the rest joining it (db.clj:23-52); kill/pause fault protocols
    via SignalProcess."""

    process_pattern = "consul"

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start(self, sess, test, node):
        nodes = test.get("nodes", [node])
        args = [BINARY, "agent", "-server",
                "-data-dir", DATA_DIR,
                "-bind", node, "-client", "0.0.0.0",
                "-node", node]
        if node == nodes[0]:
            args += ["-bootstrap-expect", str(len(nodes))]
        else:
            args += ["-retry-join", nodes[0]]
        cutil.start_daemon(sess, *args, logfile=LOGFILE,
                           pidfile=PIDFILE, chdir=DIR)

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://releases.hashicorp.com/consul/{self.version}/"
               f"consul_{self.version}_linux_amd64.zip")
        cutil.install_archive(sess, url, DIR)
        self._start(sess, test, node)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [LOGFILE]


class ConsulClient(jclient.Client):
    """KV register over the HTTP API (client.clj:48-88): reads return
    (value, ModifyIndex); `?cas=index` makes the put conditional."""

    def __init__(self, port: int = 8500, node: str | None = None,
                 timeout: float = 5.0):
        self.port = port
        self.node = node
        self.timeout = timeout

    def open(self, test, node):
        return ConsulClient(self.port, node, self.timeout)

    def _url(self, test, k, query: str = "") -> str:
        host, port = resolve(self.node, self.port, test or {})
        return f"http://{host}:{port}/v1/kv/jepsen-r{k}{query}"

    def _get(self, test, k):
        """-> (value | None, modify_index)."""
        try:
            with urllib.request.urlopen(self._url(test, k),
                                        timeout=self.timeout) as r:
                body = json.loads(r.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None, 0
            raise
        entry = body[0]
        raw = entry.get("Value")
        val = int(base64.b64decode(raw)) if raw else None
        return val, int(entry.get("ModifyIndex", 0))

    def _put(self, test, k, val, cas_index: int | None = None) -> bool:
        q = f"?cas={cas_index}" if cas_index is not None else ""
        req = urllib.request.Request(
            self._url(test, k, q), data=str(int(val)).encode(),
            method="PUT")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read()) is True

    def invoke(self, test, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        crash = "fail" if op["f"] == "read" else "info"
        try:
            if op["f"] == "read":
                cur, _idx = self._get(test, k)
                return {**op, "type": "ok", "value": lift(cur)}
            if op["f"] == "write":
                self._put(test, k, val)
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = val
                cur, idx = self._get(test, k)
                if cur != old:
                    return {**op, "type": "fail", "error": "precondition"}
                if self._put(test, k, new, cas_index=idx):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-index"}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500:
                return {**op, "type": "fail", "error": f"http-{e.code}"}
            return {**op, "type": crash, "error": f"http-{e.code}"}
        except OSError as e:
            return {**op, "type": crash, "error": str(e)[:160]}


def workloads(opts: dict | None = None) -> dict:
    from ..workloads.register import rand_op

    def register():
        return {
            "generator": independent.concurrent_generator(
                2, range(10_000),
                lambda k: gen.limit(100, rand_op)),
            "checker": independent.checker(jchecker.compose({
                "timeline": jchecker.timeline_checker(),
                "linear": jchecker.linearizable(models.cas_register()),
            })),
        }

    return {"register": register}


def consul_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wl = workloads(opts)["register"]()
    test = {
        "name": "consul register",
        "os": os_setup.debian(),
        "db": ConsulDB(opts.get("version", VERSION)),
        "client": opts.get("client") or ConsulClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": wl["checker"],
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(wl["generator"],
                        nemesis_cycle(opts.get("nemesis-interval", 10)))),
        "workload": "register",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: consul_test(tmap),
                        name="consul", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
